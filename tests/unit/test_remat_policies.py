"""Remat-policy classification (CPU guard for the TPU-side replay probe).

`tests/perf/remat_flash_probe.py` proves on the real chip that the attention
policies compile replay-free; this suite pins the POLICY CALLABLES' decisions
per-equation in CI (the width-signature logic that distinguishes the fused-qkv
and square projections must not drift)."""

import jax
import jax.numpy as jnp
import pytest
from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    checkpoint_wrapper, _flash_policy)

E = 8


def _eqns(fn, *args):
    return jax.make_jaxpr(fn)(*args).jaxpr.eqns


def _decide(policy, eqn):
    return bool(policy(eqn.primitive, *[v.aval for v in eqn.invars], **eqn.params))


def _dot_eqn(n_in, n_out):
    x = jnp.ones((4, n_in))
    w = jnp.ones((n_in, n_out))
    (eqn,) = [e for e in _eqns(lambda x, w: x @ w, x, w)
              if e.primitive.name == "dot_general"]
    return eqn


def test_flash_policy_saves_named_attention_residuals():
    pol = _flash_policy()
    (eqn,) = [e for e in _eqns(lambda x: checkpoint_name(x, "attn_out"), jnp.ones((2,)))
              if e.primitive.name == "name"]
    assert _decide(pol, eqn)
    (eqn,) = [e for e in _eqns(lambda x: checkpoint_name(x, "attn_lse"), jnp.ones((2,)))
              if e.primitive.name == "name"]
    assert _decide(pol, eqn)
    (eqn,) = [e for e in _eqns(lambda x: checkpoint_name(x, "other"), jnp.ones((2,)))
              if e.primitive.name == "name"]
    assert not _decide(pol, eqn)


@pytest.mark.parametrize("exclude,keep_qkv,qkv,square,fc,head", [
    # 'flash': drop the fused-qkv save, keep everything else
    ("qkv", False, False, True, True, True),
    # 'dots+attn-lean': keep qkv, drop the square attention projection
    ("square", True, True, False, True, True),
])
def test_flash_policy_width_signatures(exclude, keep_qkv, qkv, square, fc, head):
    pol = _flash_policy(exclude=exclude, keep_qkv=keep_qkv)
    assert _decide(pol, _dot_eqn(E, 3 * E)) == qkv        # fused qkv [E, 3E]
    assert _decide(pol, _dot_eqn(E, E)) == square          # attn proj [E, E]
    assert _decide(pol, _dot_eqn(E, 4 * E)) == fc          # mlp fc [E, 4E]
    assert _decide(pol, _dot_eqn(4 * E, E)) == head        # mlp proj [4E, E]


def test_flash_policy_refuses_colliding_qkv_widths():
    """Two DISTINCT shapes in the same exclusion class mean the width heuristic
    is ambiguous for this model — the policy must fail loudly, not silently
    drop one dot's save."""
    pol = _flash_policy(exclude="qkv", keep_qkv=False)
    assert not _decide(pol, _dot_eqn(E, 3 * E))
    with pytest.raises(ValueError, match="width-signature collision"):
        _decide(pol, _dot_eqn(2 * E, 6 * E))  # second, different fused-qkv width


def test_flash_policy_refuses_foreign_square_projection():
    """A square dot whose width disagrees with the qkv-implied embed width is
    NOT the attention output projection (e.g. an MoE/router square) and must
    not be silently excluded (ADVICE low finding)."""
    pol = _flash_policy(exclude="square", keep_qkv=True)
    assert _decide(pol, _dot_eqn(E, 3 * E))  # establishes embed width E
    with pytest.raises(ValueError, match="MoE/router square"):
        _decide(pol, _dot_eqn(2 * E, 2 * E))  # square, but at width 2E != E


def test_flash_policy_collision_raises_through_wrapper():
    """End-to-end: tracing a checkpointed block that contains a foreign square
    dot under 'dots+attn-lean' raises at trace time instead of mis-saving."""
    w_qkv = jnp.ones((E, 3 * E))
    w_moe = jnp.ones((2 * E, 2 * E))

    def block(x):
        h = x @ w_qkv                      # fused-qkv signature: embed width E
        r = jnp.ones((4, 2 * E)) @ w_moe   # square at 2E: not the attn out proj
        return h.sum() + r.sum()

    fn = checkpoint_wrapper(block, policy="dots+attn-lean")
    with pytest.raises(ValueError, match="width-signature collision"):
        jax.grad(lambda x: fn(x))(jnp.ones((4, E)))


def test_wrapper_rejects_unknown_policy():
    with pytest.raises(ValueError, match="unknown remat policy"):
        checkpoint_wrapper(lambda x: x, policy="not-a-policy")(jnp.ones((2,)))


@pytest.mark.parametrize("name", ["dots", "attn", "dots+attn", "flash",
                                  "dots+attn-lean", None])
def test_all_named_policies_differentiate(name):
    """Every named policy must produce a working checkpointed grad (numerics
    equal to the un-checkpointed oracle)."""
    w = jnp.ones((4, 4)) * 0.3

    def block(x):
        return jnp.tanh(x @ w).sum()

    x = jnp.arange(4.0).reshape(1, 4)
    g_ref = jax.grad(lambda x: block(x))(x)
    g = jax.grad(lambda x: checkpoint_wrapper(block, policy=name)(x))(x)
    assert jnp.allclose(g, g_ref)


# ---------------------------------------------------------------- tag gating
def _dot_decisions(pol, fn, *args):
    """Feed EVERY eqn to the policy in trace order (the announcements are
    stateful) and return the decisions for the dot_general eqns."""
    out = []
    for eqn in _eqns(fn, *args):
        d = _decide(pol, eqn)
        if eqn.primitive.name == "dot_general":
            out.append(d)
    return out


def test_flash_policy_tag_gated_qkv_exclusion():
    """Announced dots classify by tag, and the width heuristic is OFF in a
    tagged trace: an untagged dot with a colliding qkv width signature keeps its
    save and raises no collision error."""
    w_qkv = jnp.ones((E, 3 * E))
    w_other = jnp.ones((2 * E, 6 * E))  # same 3x signature, different width

    def block(x, y):
        t = checkpoint_name(x, "ds_dot:qkv")
        return (t @ w_qkv).sum() + (y @ w_other).sum()

    pol = _flash_policy(exclude="qkv", keep_qkv=False)
    decisions = _dot_decisions(pol, block, jnp.ones((4, E)), jnp.ones((4, 2 * E)))
    assert decisions == [False, True]  # tagged qkv dropped, untagged saved


def test_flash_policy_tag_gated_proj_exclusion():
    """'dots+attn-lean' under tags: the announced proj dot is excluded, the
    announced qkv dot is kept, and a foreign square dot neither loses its save
    nor trips the cross-validation error."""
    w_qkv = jnp.ones((E, 3 * E))
    w_proj = jnp.ones((E, E))
    w_moe = jnp.ones((2 * E, 2 * E))

    def block(x, y):
        t = checkpoint_name(x, "ds_dot:qkv")
        h = t @ w_qkv
        u = checkpoint_name(x, "ds_dot:proj")
        p = u @ w_proj
        return h.sum() + p.sum() + (y @ w_moe).sum()

    pol = _flash_policy(exclude="square", keep_qkv=True)
    decisions = _dot_decisions(pol, block, jnp.ones((4, E)), jnp.ones((4, 2 * E)))
    assert decisions == [True, False, True]


def test_tagged_block_with_foreign_square_differentiates():
    """End-to-end: the tagged-model analog of the collision scenario traces and
    differentiates cleanly under 'dots+attn-lean' (the untagged version raises —
    test_flash_policy_collision_raises_through_wrapper)."""
    w_qkv = jnp.ones((E, 3 * E)) * 0.1
    w_proj = jnp.ones((E, E)) * 0.1
    w_moe = jnp.ones((2 * E, 2 * E)) * 0.1

    def block(x):
        t = checkpoint_name(x, "ds_dot:qkv")
        h = jnp.tanh(t @ w_qkv)
        u = checkpoint_name(x, "ds_dot:proj")
        p = jnp.tanh(u @ w_proj)
        r = jnp.ones((4, 2 * E)) @ w_moe
        return h.sum() + p.sum() + r.sum()

    x = jnp.arange(4.0 * E).reshape(4, E) * 0.01
    g_ref = jax.grad(lambda x: block(x))(x)
    g = jax.grad(lambda x: checkpoint_wrapper(block, policy="dots+attn-lean")(x))(x)
    assert jnp.allclose(g, g_ref)


def test_gpt2_attention_emits_ds_dot_tags():
    """The gpt2 training forward announces its qkv and proj dots (the fused
    transformer kernel does the same — its tags are asserted by its own suite's
    policy compatibility, this pins the model-side contract)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=32, n_positions=16, n_embd=16, n_layer=1,
                     n_head=2, compute_dtype=jnp.float32,
                     use_flash_attention=False)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.zeros((1, 16), jnp.int32)
    jaxpr = jax.make_jaxpr(lambda p: model.apply(p, toks, toks))(params)

    tags = []

    def walk(jxp):
        for e in jxp.eqns:
            if e.primitive.name == "name":
                tags.append(e.params["name"])
            for v in e.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr)
    walk(jaxpr.jaxpr)
    assert "ds_dot:qkv" in tags, tags
    assert "ds_dot:proj" in tags, tags
