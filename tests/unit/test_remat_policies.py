"""Remat-policy classification (CPU guard for the TPU-side replay probe).

`tests/perf/remat_flash_probe.py` proves on the real chip that the attention
policies compile replay-free; this suite pins the POLICY CALLABLES' decisions
per-equation in CI (the width-signature logic that distinguishes the fused-qkv
and square projections must not drift)."""

import jax
import jax.numpy as jnp
import pytest
from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    checkpoint_wrapper, _flash_policy)

E = 8


def _eqns(fn, *args):
    return jax.make_jaxpr(fn)(*args).jaxpr.eqns


def _decide(policy, eqn):
    return bool(policy(eqn.primitive, *[v.aval for v in eqn.invars], **eqn.params))


def _dot_eqn(n_in, n_out):
    x = jnp.ones((4, n_in))
    w = jnp.ones((n_in, n_out))
    (eqn,) = [e for e in _eqns(lambda x, w: x @ w, x, w)
              if e.primitive.name == "dot_general"]
    return eqn


def test_flash_policy_saves_named_attention_residuals():
    pol = _flash_policy()
    (eqn,) = [e for e in _eqns(lambda x: checkpoint_name(x, "attn_out"), jnp.ones((2,)))
              if e.primitive.name == "name"]
    assert _decide(pol, eqn)
    (eqn,) = [e for e in _eqns(lambda x: checkpoint_name(x, "attn_lse"), jnp.ones((2,)))
              if e.primitive.name == "name"]
    assert _decide(pol, eqn)
    (eqn,) = [e for e in _eqns(lambda x: checkpoint_name(x, "other"), jnp.ones((2,)))
              if e.primitive.name == "name"]
    assert not _decide(pol, eqn)


@pytest.mark.parametrize("exclude,keep_qkv,qkv,square,fc,head", [
    # 'flash': drop the fused-qkv save, keep everything else
    ("qkv", False, False, True, True, True),
    # 'dots+attn-lean': keep qkv, drop the square attention projection
    ("square", True, True, False, True, True),
])
def test_flash_policy_width_signatures(exclude, keep_qkv, qkv, square, fc, head):
    pol = _flash_policy(exclude=exclude, keep_qkv=keep_qkv)
    assert _decide(pol, _dot_eqn(E, 3 * E)) == qkv        # fused qkv [E, 3E]
    assert _decide(pol, _dot_eqn(E, E)) == square          # attn proj [E, E]
    assert _decide(pol, _dot_eqn(E, 4 * E)) == fc          # mlp fc [E, 4E]
    assert _decide(pol, _dot_eqn(4 * E, E)) == head        # mlp proj [4E, E]


def test_flash_policy_refuses_colliding_qkv_widths():
    """Two DISTINCT shapes in the same exclusion class mean the width heuristic
    is ambiguous for this model — the policy must fail loudly, not silently
    drop one dot's save."""
    pol = _flash_policy(exclude="qkv", keep_qkv=False)
    assert not _decide(pol, _dot_eqn(E, 3 * E))
    with pytest.raises(ValueError, match="width-signature collision"):
        _decide(pol, _dot_eqn(2 * E, 6 * E))  # second, different fused-qkv width


def test_flash_policy_refuses_foreign_square_projection():
    """A square dot whose width disagrees with the qkv-implied embed width is
    NOT the attention output projection (e.g. an MoE/router square) and must
    not be silently excluded (ADVICE low finding)."""
    pol = _flash_policy(exclude="square", keep_qkv=True)
    assert _decide(pol, _dot_eqn(E, 3 * E))  # establishes embed width E
    with pytest.raises(ValueError, match="MoE/router square"):
        _decide(pol, _dot_eqn(2 * E, 2 * E))  # square, but at width 2E != E


def test_flash_policy_collision_raises_through_wrapper():
    """End-to-end: tracing a checkpointed block that contains a foreign square
    dot under 'dots+attn-lean' raises at trace time instead of mis-saving."""
    w_qkv = jnp.ones((E, 3 * E))
    w_moe = jnp.ones((2 * E, 2 * E))

    def block(x):
        h = x @ w_qkv                      # fused-qkv signature: embed width E
        r = jnp.ones((4, 2 * E)) @ w_moe   # square at 2E: not the attn out proj
        return h.sum() + r.sum()

    fn = checkpoint_wrapper(block, policy="dots+attn-lean")
    with pytest.raises(ValueError, match="width-signature collision"):
        jax.grad(lambda x: fn(x))(jnp.ones((4, E)))


def test_wrapper_rejects_unknown_policy():
    with pytest.raises(ValueError, match="unknown remat policy"):
        checkpoint_wrapper(lambda x: x, policy="not-a-policy")(jnp.ones((2,)))


@pytest.mark.parametrize("name", ["dots", "attn", "dots+attn", "flash",
                                  "dots+attn-lean", None])
def test_all_named_policies_differentiate(name):
    """Every named policy must produce a working checkpointed grad (numerics
    equal to the un-checkpointed oracle)."""
    w = jnp.ones((4, 4)) * 0.3

    def block(x):
        return jnp.tanh(x @ w).sum()

    x = jnp.arange(4.0).reshape(1, 4)
    g_ref = jax.grad(lambda x: block(x))(x)
    g = jax.grad(lambda x: checkpoint_wrapper(block, policy=name)(x))(x)
    assert jnp.allclose(g, g_ref)
