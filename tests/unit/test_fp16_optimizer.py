"""FP16_Optimizer wrapper tests (reference tests/unit/test_fp16.py + dynamic loss
scale tests: overflow skip, scale halving/doubling, LAMB variant, checkpoint)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.fp16 import FP16_Optimizer, FP16_UnfusedOptimizer


def _params(key=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(key))
    return {"w": jax.random.normal(k1, (8, 4), jnp.float32) * 0.1,
            "b": jnp.zeros((4,), jnp.float32)}


def _loss_fn(p, x, y):
    pred = x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


@pytest.fixture
def batch():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    w_true = rng.normal(size=(8, 4)).astype(np.float32)
    return x, jnp.asarray(x @ w_true)


def test_training_decreases_loss(batch):
    opt = FP16_Optimizer(_params(), optimizer="adamw", lr=5e-2, compute_dtype=jnp.bfloat16)
    p16 = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), _params())
    losses = []
    for _ in range(30):
        loss, grads = opt.backward(_loss_fn, p16, *batch)
        p16 = opt.step(grads)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses


def test_overflow_skips_step_and_halves_scale(batch):
    opt = FP16_Optimizer(_params(), dynamic_loss_scale=True, initial_scale_power=4,
                         hysteresis=1, lr=1e-2)
    master_before = jax.device_get(opt.master)
    scale_before = opt.cur_scale
    bad = jax.tree_util.tree_map(lambda p: jnp.full_like(p, jnp.inf), opt.master)
    opt.step(bad)
    assert opt.overflow
    assert opt.cur_scale == scale_before / 2
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                           master_before, jax.device_get(opt.master))
    assert int(jax.device_get(opt.steps)) == 0


def test_hysteresis_delays_scale_drop():
    opt = FP16_Optimizer(_params(), dynamic_loss_scale=True, initial_scale_power=4, hysteresis=2)
    s0 = opt.cur_scale
    bad = jax.tree_util.tree_map(lambda p: jnp.full_like(p, jnp.nan), opt.master)
    opt.step(bad)
    assert opt.cur_scale == s0  # first overflow only consumes hysteresis
    opt.step(bad)
    assert opt.cur_scale == s0 / 2


def test_scale_doubles_after_window(batch):
    opt = FP16_Optimizer(_params(), dynamic_loss_scale=True, initial_scale_power=4,
                         scale_window=3, lr=1e-3)
    s0 = opt.cur_scale
    p16 = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), _params())
    for _ in range(3):
        _, grads = opt.backward(_loss_fn, p16, *batch)
        p16 = opt.step(grads)
    assert opt.cur_scale == s0 * 2


def test_static_scale_never_moves(batch):
    opt = FP16_Optimizer(_params(), static_loss_scale=128.0, dynamic_loss_scale=False)
    assert opt.cur_scale == 128.0
    bad = jax.tree_util.tree_map(lambda p: jnp.full_like(p, jnp.inf), opt.master)
    opt.step(bad)
    assert opt.cur_scale == 128.0


def test_clip_grad_limits_update(batch):
    """Adam is scale-invariant, so clip is observable through an SGD inner rule
    (this also exercises the custom inner_apply hook)."""
    def sgd_apply(grads, state, master, step, hyper):
        new = jax.tree_util.tree_map(lambda p, g: p - hyper["lr"] * g, master, grads)
        return new, state

    opt = FP16_Optimizer(_params(), clip_grad=1e-3, lr=1.0, dynamic_loss_scale=False,
                         static_loss_scale=1.0,
                         inner_apply=sgd_apply, inner_init=lambda m: {})
    huge = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 100.0), opt.master)
    before = jax.device_get(opt.master)
    opt.step(huge)
    after = jax.device_get(opt.master)
    # global grad norm clipped to 1e-3 → per-element delta bounded by it
    max_delta = max(float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
                    for a, b in zip(jax.tree_util.tree_leaves(after),
                                    jax.tree_util.tree_leaves(before)))
    assert max_delta <= 1.1e-3


def test_lamb_unfused_variant(batch):
    opt = FP16_UnfusedOptimizer(_params(), lr=0.1)
    p16 = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), _params())
    losses = []
    for _ in range(40):
        loss, grads = opt.backward(_loss_fn, p16, *batch)
        p16 = opt.step(grads)
        losses.append(float(loss))
    assert losses[-1] < 0.6 * losses[0], losses


def test_state_dict_roundtrip(batch):
    opt = FP16_Optimizer(_params(), lr=1e-2)
    p16 = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), _params())
    for _ in range(3):
        _, grads = opt.backward(_loss_fn, p16, *batch)
        p16 = opt.step(grads)
    sd = jax.device_get(opt.state_dict())

    opt2 = FP16_Optimizer(_params(7), lr=1e-2)
    opt2.load_state_dict(sd)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                           jax.device_get(opt.master), jax.device_get(opt2.master))
    assert opt2.cur_scale == opt.cur_scale
