"""Metric catalog tests (docs/metrics.md).

The catalog (utils/metrics.py) is the single declaration point for every
scalar name any observatory emits: unit, direction, class, description. Two
contracts ride on it:

  1. ROUTING — SummaryMonitor.add_scalar feeds the per-host metric ring
     through the catalog on EVERY rank (before the rank-0 early return), so
     undeclared names warn exactly once (or raise in strict mode) and every
     host's flight-recorder dump carries a mergeable ring.
  2. DIRECTION — bench.py derives its lower-is-better regression set from
     the catalog instead of a private frozenset, so a new bench key without
     a declared metric is a test failure, not a silently-unflagged number.

The drift guard at the bottom runs a REAL engine with a strict-mode store
attached, so any emitter that grows an undeclared scalar name fails here
before it ships.
"""

import json
import logging
import os
import sys

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import logger
from deepspeed_tpu.utils.metrics import (DEFAULT_RING_LEN, MetricCatalog,
                                         MetricStore, UnknownMetricError,
                                         default_catalog, export_store,
                                         merge_host_rings, openmetrics_name,
                                         openmetrics_text)
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16
ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ------------------------------------------------------------- resolution


def test_exact_names_resolve():
    cat = default_catalog()
    for name in ("Telemetry/Samples/step_time_ms", "Telemetry/Samples/mfu",
                 "Train/Samples/train_loss", "Train/Samples/loss_scale",
                 "Cluster/step_skew", "Serving/tok_s", "Serving/ttft_ms",
                 "Serving/Fleet/shed", "Serving/Fleet/Goodput/fraction",
                 "Profile/exposed_ici_ms", "Run/Goodput/goodput_fraction",
                 "Pipeline/Goodput/bubble_fraction"):
        spec = cat.resolve(name)
        assert spec is not None, f"{name} undeclared"
        assert spec.unit and spec.description
        assert spec.direction in ("lower_is_better", "higher_is_better",
                                  "neutral")


def test_family_resolution_longest_prefix_wins():
    """Serving/Fleet/Latency/* must shadow the Serving/* catch-all, and an
    exact declaration must beat any family that also matches."""
    cat = default_catalog()
    fleet_p99 = cat.resolve("Serving/Fleet/Latency/ttft_ms_p99")
    assert fleet_p99 is not None
    assert fleet_p99.pattern == "Serving/Fleet/Latency/*"
    assert fleet_p99.direction == "lower_is_better"
    # the catch-all still covers genuinely novel serving scalars
    novel = cat.resolve("Serving/some_future_scalar")
    assert novel is not None and novel.pattern == "Serving/*"
    # exact beats prefix: Serving/tok_s has its own declaration
    assert cat.resolve("Serving/tok_s").pattern == "Serving/tok_s"
    assert cat.resolve("Serving/tok_s").direction == "higher_is_better"


def test_undeclared_name_resolves_none():
    cat = default_catalog()
    assert cat.resolve("Nonsense/made_up") is None
    assert cat.direction("Nonsense/made_up") is None


def test_alerts_family_is_declared():
    """The alert plane's own emissions must route through the same catalog."""
    spec = default_catalog().resolve("Alerts/mfu_drop")
    assert spec is not None and spec.pattern == "Alerts/*"


def test_duplicate_exact_declaration_raises():
    from deepspeed_tpu.utils.metrics import _spec
    dup = [_spec("X/a", "1", "neutral", "test", "one"),
           _spec("X/a", "1", "neutral", "test", "two")]
    with pytest.raises(ValueError, match="duplicate"):
        MetricCatalog(dup)


# ------------------------------------------------------------ metric store


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.records = []

    def emit(self, record):
        self.records.append(record)

    @property
    def text(self):
        return "\n".join(r.getMessage() for r in self.records)


def test_ring_is_bounded_and_ordered():
    store = MetricStore(ring_len=4)
    for step in range(10):
        store.observe("Telemetry/Samples/mfu", 0.1 * step, step)
    series = store.series("Telemetry/Samples/mfu")
    assert len(series) == 4  # fixed geometry: oldest observations evicted
    assert [s for s, _ in series] == [6, 7, 8, 9]
    assert store.last("Telemetry/Samples/mfu") == (9, pytest.approx(0.9))
    assert store.observations == 10  # counts everything ever observed


def test_unknown_metric_warns_exactly_once():
    h = _Capture()
    logger.addHandler(h)
    try:
        store = MetricStore(strict=False)
        store.observe("Bogus/thing", 1.0, 0)
        store.observe("Bogus/thing", 2.0, 1)
        store.observe("Bogus/other", 1.0, 0)
    finally:
        logger.removeHandler(h)
    warnings = [r for r in h.records if "not in the MetricCatalog" in
                r.getMessage()]
    assert len(warnings) == 2  # one per distinct name, not per observation
    # untyped observations are still recorded — warn, don't drop
    assert len(store.series("Bogus/thing")) == 2


def test_strict_store_raises_on_undeclared():
    store = MetricStore(strict=True)
    store.observe("Telemetry/Samples/mfu", 0.5, 0)  # declared: fine
    with pytest.raises(UnknownMetricError, match="Bogus/thing"):
        store.observe("Bogus/thing", 1.0, 0)


def test_ring_len_must_be_positive():
    with pytest.raises(ValueError, match="ring_len"):
        MetricStore(ring_len=0)


def test_monitor_routes_every_rank(tmp_path):
    """The catalog hook in SummaryMonitor.add_scalar runs BEFORE the rank-0
    enabled early-return: a disabled (non-rank-0) monitor still feeds the
    ring, because every host's dump must carry its own metrics."""
    from deepspeed_tpu.utils.monitor import SummaryMonitor
    mon = SummaryMonitor(enabled=False, output_path=str(tmp_path),
                         job_name="m")
    store = MetricStore(ring_len=8, host=3)
    mon.metrics = store
    mon.add_scalar("Telemetry/Samples/mfu", 0.42, 7)
    assert store.last("Telemetry/Samples/mfu") == (7, pytest.approx(0.42))
    # the disabled monitor itself wrote nothing
    assert not os.path.exists(os.path.join(str(tmp_path), "m",
                                           "scalars.jsonl"))


# ------------------------------------------------------------- fleet merge


def _ring(host, ring_len=8, **series):
    store = MetricStore(ring_len=ring_len, host=host)
    for name, obs in series.items():
        for step, value in obs:
            store.observe(name.replace("__", "/"), value, step)
    return store.to_dict()


def test_merge_host_rings_exact_union():
    a = _ring(0, Telemetry__Samples__mfu=[(0, 0.4), (1, 0.41)])
    b = _ring(1, Telemetry__Samples__mfu=[(0, 0.39)],
              Cluster__step_skew=[(1, 1.2)])
    merged = merge_host_rings({0: a, 1: b})
    assert merged["hosts"] == [0, 1] and merged["ring_len"] == 8
    mfu = merged["series"]["Telemetry/Samples/mfu"]
    assert mfu[0] == [[0, 0.4], [1, 0.41]]  # lossless: nothing reduced away
    assert mfu[1] == [[0, 0.39]]
    assert merged["series"]["Cluster/step_skew"] == {1: [[1, 1.2]]}
    # deterministic: same inputs -> byte-identical JSON
    again = merge_host_rings({1: b, 0: a})
    assert json.dumps(merged, sort_keys=True) == json.dumps(again,
                                                            sort_keys=True)


def test_merge_refuses_geometry_mismatch():
    a = _ring(0, ring_len=8, Telemetry__Samples__mfu=[(0, 0.4)])
    b = _ring(1, ring_len=16, Telemetry__Samples__mfu=[(0, 0.4)])
    with pytest.raises(ValueError, match="geometry"):
        merge_host_rings({0: a, 1: b})


# ------------------------------------------------------ OpenMetrics export


def test_openmetrics_name_mangling():
    assert openmetrics_name("Telemetry/Samples/mfu") == "telemetry_samples_mfu"
    assert openmetrics_name("Serving/Fleet/Latency/ttft_ms_p99") == \
        "serving_fleet_latency_ttft_ms_p99"


def test_openmetrics_export_latest_only(tmp_path):
    store = MetricStore(ring_len=8, host=2)
    store.observe("Telemetry/Samples/mfu", 0.40, 1)
    store.observe("Telemetry/Samples/mfu", 0.43, 2)  # only this one exports
    text = openmetrics_text(store.to_dict())
    assert '# TYPE telemetry_samples_mfu gauge' in text
    assert '# UNIT telemetry_samples_mfu' in text
    assert '# HELP telemetry_samples_mfu' in text
    assert 'telemetry_samples_mfu{host="2",step="2"} 0.43' in text
    assert 'step="1"' not in text
    assert text.endswith("# EOF\n")
    path = export_store(store, str(tmp_path / "om" / "metrics.txt"))
    assert open(path).read() == text


# -------------------------------------------------------- bench directions


def _bench():
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    import bench
    return bench


def test_every_regression_key_has_a_declared_metric():
    """Satellite contract: bench keeps NO private direction list — every
    regression key maps to a catalog metric with a real (non-neutral)
    direction, so 'which way is worse' has exactly one source of truth."""
    bench = _bench()
    cat = default_catalog()
    assert set(bench.REGRESSION_KEYS) == set(bench.REGRESSION_KEY_METRICS), \
        "regression keys and their catalog mapping drifted apart"
    for key, metric in bench.REGRESSION_KEY_METRICS.items():
        spec = cat.resolve(metric)
        assert spec is not None, f"{key} -> {metric}: undeclared metric"
        assert spec.direction != "neutral", \
            f"{key} -> {metric}: neutral direction can't drive a regression flag"


def test_private_direction_list_is_retired():
    bench = _bench()
    assert not hasattr(bench, "LOWER_IS_BETTER_KEYS"), \
        "bench grew its private direction list back"


def test_catalog_reproduces_the_retired_membership():
    """The catalog-derived set must equal the frozenset bench shipped before
    this PR — retiring the list must not silently flip any key's direction."""
    bench = _bench()
    retired = frozenset(
        k for k in bench.REGRESSION_KEYS
        if k.endswith("_ms_p50") or k.endswith("_ms_p95")) | frozenset({
            "extra.resilience.checkpoint_stall_ms",
            "extra.resilience.restore_warm_vs_cold_ttft",
            "extra.goodput.badput_checkpoint_pct",
            "extra.serving_speculative.target_steps_per_token",
            "extra.serving_1p5b_spec.target_steps_per_token",
            "extra.serving_fleet.fleet_p99_ttft_ms",
            "extra.serving_fleet.shed_rate",
            "extra.serving_fleet.shed_rate_2x_saturation",
            "extra.hbm.peak_by_class.params",
            "extra.hbm.peak_by_class.grads",
            "extra.hbm.peak_by_class.master",
            "extra.hbm.peak_by_class.optimizer",
            "extra.hbm.peak_by_class.compiled_temp_peak",
            "extra.profile.exposed_ici_ms",
            "extra.profile.exposed_dcn_ms",
            "extra.profile.host_gap_ms",
        })
    assert bench.lower_is_better_keys() == retired


# --------------------------------------------------------- catalog drift guard


def _build(**overrides):
    import jax
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def test_live_emission_paths_stay_in_catalog(tmp_path):
    """Drift guard: a strict-mode store over a real engine run — telemetry,
    memory manifest, numerics and the train loop all emitting — must never
    see an undeclared scalar name. A new emitter that forgets its catalog
    declaration fails HERE, not as a warn-once line in some run log."""
    eng = _build(tensorboard={"enabled": True,
                              "output_path": str(tmp_path),
                              "job_name": "drift"},
                 telemetry={"enabled": True, "peak_tflops": 1e-6,
                            "mfu_window": 4, "output_path": str(tmp_path),
                            "job_name": "drift",
                            "metrics": {"enabled": True,
                                        "strict_catalog": True,
                                        "ring_len": 64}})
    assert eng.telemetry.metric_store is not None
    assert eng.telemetry.metric_store.strict
    xs, ys = _batchpair()
    for _ in range(4):  # raises UnknownMetricError on any undeclared name
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    eng.telemetry.close()
    store = eng.telemetry.metric_store
    assert store.observations > 0
    assert store.last("Telemetry/Samples/step_time_ms") is not None
    assert store.last("Train/Samples/train_loss") is not None


def _batchpair(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))
