"""Checkpoint round-trip tests (parity with reference tests/unit/test_checkpointing.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from oldjax import grad_through_shard_map_xfail
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def make_engine(cfg, seed=0, hidden=HIDDEN):
    model = SimpleModel(hidden)
    params = model.init(jax.random.PRNGKey(seed))
    data = random_dataset(128, hidden, seed=seed)
    engine, _, loader, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                    training_data=data, config_params=cfg)
    return engine, loader


def train_steps(engine, loader, n):
    it = iter(loader)
    for _ in range(n):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    return it


def trees_equal(a, b, rtol=0.0, atol=0.0):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float32), np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("zero_stage", [0, 1, 2])
def test_checkpoint_roundtrip(tmp_path, zero_stage):
    cfg = simple_config(zero_optimization={"stage": zero_stage})
    engine, loader = make_engine(cfg)
    train_steps(engine, loader, 3)
    engine.save_checkpoint(str(tmp_path), client_state={"note": "hello"})

    engine2, _ = make_engine(cfg, seed=99)  # different init
    path, client_state = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client_state == {"note": "hello"}
    assert engine2.global_steps == engine.global_steps
    trees_equal(engine.master_params, engine2.master_params)
    trees_equal(engine.opt_state, engine2.opt_state)
    trees_equal(engine.params, engine2.params)


def test_checkpoint_continue_training_matches(tmp_path):
    """Save at step 3, keep training to 6; reload at 3 and retrain — same weights."""
    cfg = simple_config()
    engine, loader = make_engine(cfg)
    it = iter(loader)
    batches = []
    for _ in range(6):
        batches.append(next(it))
    for x, y in batches[:3]:
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(str(tmp_path))
    for x, y in batches[3:]:
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    final_a = jax.device_get(engine.master_params)

    engine2, _ = make_engine(cfg, seed=7)
    engine2.load_checkpoint(str(tmp_path))
    for x, y in batches[3:]:
        loss = engine2(x, y)
        engine2.backward(loss)
        engine2.step()
    final_b = jax.device_get(engine2.master_params)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
                           final_a, final_b)


def test_checkpoint_lr_scheduler_state(tmp_path):
    cfg = simple_config(scheduler={"type": "WarmupLR",
                                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                              "warmup_num_steps": 20}})
    engine, loader = make_engine(cfg)
    train_steps(engine, loader, 5)
    saved_iter = engine.lr_scheduler.last_batch_iteration
    engine.save_checkpoint(str(tmp_path))

    engine2, _ = make_engine(cfg)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.lr_scheduler.last_batch_iteration == saved_iter


def test_checkpoint_no_optim_states(tmp_path):
    cfg = simple_config()
    engine, loader = make_engine(cfg)
    train_steps(engine, loader, 3)
    engine.save_checkpoint(str(tmp_path))
    engine2, _ = make_engine(cfg, seed=42)
    engine2.load_checkpoint(str(tmp_path), load_optimizer_states=False)
    # params restored; master derived from (possibly lower-precision) params
    trees_equal(engine.params, engine2.params)


def test_checkpoint_latest_tag(tmp_path):
    cfg = simple_config()
    engine, loader = make_engine(cfg)
    train_steps(engine, loader, 1)
    engine.save_checkpoint(str(tmp_path), tag="step1")
    train_steps(engine, loader, 1)
    engine.save_checkpoint(str(tmp_path), tag="step2")
    assert (tmp_path / "latest").read_text() == "step2"
    engine2, _ = make_engine(cfg, seed=5)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path.endswith("step2")


def test_checkpoint_missing_dir():
    cfg = simple_config()
    engine, _ = make_engine(cfg)
    path, client_state = engine.load_checkpoint("/tmp/definitely_missing_dir_xyz")
    assert path is None
    assert client_state == {}


def test_checkpoint_elastic_world_size_change(tmp_path, eight_devices):
    """Save under dp=8, reload under dp=4 (elastic resharding; reference stage2.py:1713-1779)."""
    import jax
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg = simple_config(zero_optimization={"stage": 2})
    engine, loader = make_engine(cfg)
    assert engine.dp_size == 8
    train_steps(engine, loader, 3)
    engine.save_checkpoint(str(tmp_path))

    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(42))
    mesh4 = build_mesh(data=4, model=1, pipe=1, devices=eight_devices[:4])
    engine2 = DeepSpeedEngine(model=model, model_parameters=params,
                              config_params=simple_config(batch=4, zero_optimization={"stage": 2}),
                              mesh=mesh4)
    assert engine2.dp_size == 4
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    trees_equal(engine.master_params, engine2.master_params)
    trees_equal(engine.opt_state, engine2.opt_state)
    assert engine2.global_steps == engine.global_steps


def test_checkpoint_elastic_grow(tmp_path, eight_devices):
    """Save under dp=4, reload under dp=8 (elastic regrow; reference stage1.py:836-947
    supports arbitrary saved→current dp)."""
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    model = SimpleModel(HIDDEN)
    mesh4 = build_mesh(data=4, model=1, pipe=1, devices=eight_devices[:4])
    engine = DeepSpeedEngine(model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
                             config_params=simple_config(batch=4, zero_optimization={"stage": 2}),
                             mesh=mesh4)
    data = random_dataset(64, HIDDEN, seed=0)
    it = iter(engine.deepspeed_io(data))
    for _ in range(3):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(str(tmp_path))

    engine2, _ = make_engine(simple_config(zero_optimization={"stage": 2}), seed=9)
    assert engine2.dp_size == 8
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    trees_equal(engine.master_params, engine2.master_params)
    trees_equal(engine.opt_state, engine2.opt_state)


def test_checkpoint_elastic_zero3(tmp_path, eight_devices):
    """Stage-3 checkpoints resize too: save under dp=8, resume under dp=4 — the
    restored compute params re-adopt the NEW mesh's stage-3 sharded layout and
    training numerics carry over (params/master/opt all agree with the source)."""
    import jax
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    engine, loader = make_engine(simple_config(zero_optimization={"stage": 3},
                                               bf16={"enabled": True}),
                                 hidden=64)  # > min_size so the leaves shard
    train_steps(engine, loader, 3)
    engine.save_checkpoint(str(tmp_path))

    model = SimpleModel(64)
    mesh4 = build_mesh(data=4, model=1, pipe=1, devices=eight_devices[:4])
    engine2 = DeepSpeedEngine(model=model, model_parameters=model.init(jax.random.PRNGKey(42)),
                              config_params=simple_config(batch=4,
                                                          zero_optimization={"stage": 3},
                                                          bf16={"enabled": True}),
                              mesh=mesh4)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    trees_equal(engine.master_params, engine2.master_params)
    trees_equal(engine.opt_state, engine2.opt_state)
    trees_equal(engine.params, engine2.params)
    # and the restored params are sharded over the NEW (dp=4) data axis
    for leaf in jax.tree_util.tree_leaves(engine2.params):
        if leaf.ndim == 2:
            assert not leaf.sharding.is_fully_replicated
            assert leaf.addressable_shards[0].data.size * 4 == leaf.size


@grad_through_shard_map_xfail
def test_checkpoint_pipe_topology_change(tmp_path):
    """Pipeline checkpoints are layer-keyed, so stage boundaries can move between
    save and load (reference pipe/module.py:536-567, test_checkpointing.py:617+)."""
    from deepspeed_tpu.parallel.pipe import LayerSpec, PipelineModule

    class Linear:
        def __init__(self, dim):
            self.dim = dim
        def init(self, rng, x):
            k1, _ = jax.random.split(rng)
            return {"w": jax.random.normal(k1, (x.shape[-1], self.dim), jnp.float32) * 0.3}
        def apply(self, p, x):
            return jnp.tanh(x @ p["w"].astype(x.dtype))

    def mse(out, tgt):
        return jnp.mean(jnp.square(out.astype(jnp.float32) - tgt.astype(jnp.float32)))

    def build(num_stages):
        module = PipelineModule(layers=[LayerSpec(Linear, HIDDEN) for _ in range(4)],
                                num_stages=num_stages, loss_fn=mse)
        params = module.init_params(jax.random.PRNGKey(1), jnp.zeros((4, HIDDEN), jnp.float32))
        cfg = {"train_batch_size": 32, "gradient_accumulation_steps": 2,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
        engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                                   config_params=cfg)
        return engine

    def data_iter():
        rng = np.random.default_rng(3)
        while True:
            x = rng.normal(size=(16, HIDDEN)).astype(np.float32)
            yield x, np.tanh(x @ np.ones((HIDDEN, HIDDEN), np.float32) * 0.1)

    engine = build(num_stages=2)
    it = data_iter()
    for _ in range(3):
        engine.train_batch(it)
    engine.save_checkpoint(str(tmp_path))

    for new_stages in (1, 4):
        engine2 = build(num_stages=new_stages)
        path, _ = engine2.load_checkpoint(str(tmp_path))
        assert path is not None, f"reload at {new_stages} stages failed"
        # compare in the canonical layer-keyed representation: the SPMD executor
        # stores core stages pipe-stacked, and stage counts differ across engines
        trees_equal(engine.canonical_master_params(),
                    engine2.canonical_master_params())
        # training continues identically after the re-partition
        e1_it, e2_it = data_iter(), data_iter()
        l1 = float(jax.device_get(engine.eval_batch(e1_it)))
        l2 = float(jax.device_get(engine2.eval_batch(e2_it)))
        np.testing.assert_allclose(l1, l2, rtol=1e-5)
