"""Monitor tests: JSONL scalar sink + engine tensorboard-config wiring."""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils.monitor import SummaryMonitor
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def test_monitor_writes_jsonl(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "job1")
    mon.add_scalar("Train/loss", 1.5, 10)
    mon.add_scalar("Train/loss", 1.25, 20)
    mon.close()
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "job1", "scalars.jsonl"))]
    assert [l["value"] for l in lines] == [1.5, 1.25]
    assert [l["step"] for l in lines] == [10, 20]
    assert all(l["tag"] == "Train/loss" for l in lines)


def test_monitor_disabled_is_noop(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "job2", enabled=False)
    mon.add_scalar("x", 1.0, 0)  # must not raise or create files
    mon.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "job2"))


def test_monitor_event_api_writes_events_jsonl(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "jobev")
    mon.event("loss_scale", {"kind": "backoff", "scale": 64.0}, step=3)
    mon.event("desync_audit", {"divergence": None})  # step-less event
    mon.close()
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "jobev", "events.jsonl"))]
    assert [l["event"] for l in lines] == ["loss_scale", "desync_audit"]
    assert lines[0]["step"] == 3 and lines[0]["payload"]["kind"] == "backoff"
    assert lines[1]["step"] is None


def test_monitor_event_disabled_is_noop(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "jobev2", enabled=False)
    mon.event("x", {"y": 1}, step=0)  # must not raise or create files
    mon.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "jobev2"))


def test_monitor_event_file_is_lazy(tmp_path):
    """Scalar-only jobs must not grow an empty events.jsonl."""
    mon = SummaryMonitor(str(tmp_path), "jobev3")
    mon.add_scalar("x", 1.0, 0)
    mon.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "jobev3", "events.jsonl"))


def test_monitor_disabled_still_exposes_log_dir(tmp_path):
    """Regression: the disabled early-return used to skip the log_dir assignment,
    so any rank-agnostic caller touching monitor.log_dir raised AttributeError."""
    mon = SummaryMonitor(str(tmp_path), "job3", enabled=False)
    assert mon.log_dir == os.path.join(str(tmp_path), "job3")
    mon_default = SummaryMonitor(enabled=False)
    assert isinstance(mon_default.log_dir, str) and mon_default.log_dir


def test_engine_emits_scalars(tmp_path):
    cfg = simple_config()
    cfg["tensorboard"] = {"enabled": True, "output_path": str(tmp_path), "job_name": "run0"}
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    data = random_dataset(64, HIDDEN, seed=0)
    engine, _, loader, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                    training_data=data, config_params=cfg)
    assert engine.monitor is not None
    it = iter(loader)
    for _ in range(3):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.monitor.close()
    scalars = [json.loads(l) for l in
               open(os.path.join(str(tmp_path), "run0", "scalars.jsonl"))]
    tags = {s["tag"] for s in scalars}
    assert "Train/Samples/train_loss" in tags
    assert "Train/Samples/lr" in tags
    losses = [s for s in scalars if s["tag"] == "Train/Samples/train_loss"]
    assert len(losses) == 3
    assert all(np.isfinite(s["value"]) for s in losses)
    # samples axis = step * global batch
    assert losses[0]["step"] == engine.train_batch_size()


def _kill_mid_step_script(log_root, trigger):
    """Child process: write a few scalars into a block-buffered monitor,
    optionally trigger the flight recorder, then die hard (os._exit skips
    every atexit/flush hook — the SIGKILL shape of a crashing host)."""
    return f"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from types import SimpleNamespace
from deepspeed_tpu.utils.monitor import SummaryMonitor
from deepspeed_tpu.utils.numerics import FlightRecorder
mon = SummaryMonitor({log_root!r}, "kill")
for step in range(4):
    mon.add_scalar("Train/Samples/train_loss", 1.0 + step, step)
tel = SimpleNamespace(monitor=mon, watchdog=None)
rec = FlightRecorder(capacity=8, dump_dir={log_root!r}, telemetry=tel)
if {trigger!r} == "trigger":
    rec.trigger("test_kill", {{}})
os._exit(1)
"""


def _run_kill_child(tmp_path, trigger):
    import subprocess
    import sys
    root = str(tmp_path / trigger)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c",
                           _kill_mid_step_script(root, trigger)],
                          env=env, capture_output=True, text=True)
    assert proc.returncode == 1, proc.stderr
    path = os.path.join(root, "kill", "scalars.jsonl")
    return open(path).read() if os.path.exists(path) else ""


def test_flight_recorder_dump_flushes_scalars_before_kill(tmp_path):
    """Regression (buffering fix): scalars.jsonl is block-buffered — a few
    small records sit in userspace until flush(). The flight recorder MUST
    flush the monitor before dumping, so a post-mortem box sees the scalars
    that led up to the crash even when the process dies without atexit."""
    text = _run_kill_child(tmp_path, "trigger")
    lines = [json.loads(l) for l in text.splitlines()]
    assert len(lines) == 4, "dump path lost buffered scalars"
    assert [l["step"] for l in lines] == [0, 1, 2, 3]


def test_kill_without_dump_proves_the_buffer(tmp_path):
    """Companion control: with NO flight-recorder trigger the same child
    loses its buffered tail on os._exit — proving the first test exercises
    the flush-inside-dump path, not line buffering."""
    text = _run_kill_child(tmp_path, "none")
    assert text == "", "scalars survived without a flush: buffering changed?"
