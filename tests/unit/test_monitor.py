"""Monitor tests: JSONL scalar sink + engine tensorboard-config wiring."""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils.monitor import SummaryMonitor
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def test_monitor_writes_jsonl(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "job1")
    mon.add_scalar("Train/loss", 1.5, 10)
    mon.add_scalar("Train/loss", 1.25, 20)
    mon.close()
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "job1", "scalars.jsonl"))]
    assert [l["value"] for l in lines] == [1.5, 1.25]
    assert [l["step"] for l in lines] == [10, 20]
    assert all(l["tag"] == "Train/loss" for l in lines)


def test_monitor_disabled_is_noop(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "job2", enabled=False)
    mon.add_scalar("x", 1.0, 0)  # must not raise or create files
    mon.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "job2"))


def test_monitor_event_api_writes_events_jsonl(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "jobev")
    mon.event("loss_scale", {"kind": "backoff", "scale": 64.0}, step=3)
    mon.event("desync_audit", {"divergence": None})  # step-less event
    mon.close()
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "jobev", "events.jsonl"))]
    assert [l["event"] for l in lines] == ["loss_scale", "desync_audit"]
    assert lines[0]["step"] == 3 and lines[0]["payload"]["kind"] == "backoff"
    assert lines[1]["step"] is None


def test_monitor_event_disabled_is_noop(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "jobev2", enabled=False)
    mon.event("x", {"y": 1}, step=0)  # must not raise or create files
    mon.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "jobev2"))


def test_monitor_event_file_is_lazy(tmp_path):
    """Scalar-only jobs must not grow an empty events.jsonl."""
    mon = SummaryMonitor(str(tmp_path), "jobev3")
    mon.add_scalar("x", 1.0, 0)
    mon.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "jobev3", "events.jsonl"))


def test_monitor_disabled_still_exposes_log_dir(tmp_path):
    """Regression: the disabled early-return used to skip the log_dir assignment,
    so any rank-agnostic caller touching monitor.log_dir raised AttributeError."""
    mon = SummaryMonitor(str(tmp_path), "job3", enabled=False)
    assert mon.log_dir == os.path.join(str(tmp_path), "job3")
    mon_default = SummaryMonitor(enabled=False)
    assert isinstance(mon_default.log_dir, str) and mon_default.log_dir


def test_engine_emits_scalars(tmp_path):
    cfg = simple_config()
    cfg["tensorboard"] = {"enabled": True, "output_path": str(tmp_path), "job_name": "run0"}
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    data = random_dataset(64, HIDDEN, seed=0)
    engine, _, loader, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                    training_data=data, config_params=cfg)
    assert engine.monitor is not None
    it = iter(loader)
    for _ in range(3):
        x, y = next(it)
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.monitor.close()
    scalars = [json.loads(l) for l in
               open(os.path.join(str(tmp_path), "run0", "scalars.jsonl"))]
    tags = {s["tag"] for s in scalars}
    assert "Train/Samples/train_loss" in tags
    assert "Train/Samples/lr" in tags
    losses = [s for s in scalars if s["tag"] == "Train/Samples/train_loss"]
    assert len(losses) == 3
    assert all(np.isfinite(s["value"]) for s in losses)
    # samples axis = step * global batch
    assert losses[0]["step"] == engine.train_batch_size()
