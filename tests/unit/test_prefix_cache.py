"""Cross-request prefix cache: allocator cached tier, hit/remap semantics,
warm preempt-restarts, and the determinism contracts that keep serve-sim
replayable with the cache on.

Layers under test, bottom-up:

* **Allocator cached tier** (serve/block_allocator.py) — LRU park/evict/
  revive ordering, eviction strictly before admission refusal, refcount
  interaction with fork/CoW.
* **PrefixCache** (serve/prefix_cache.py) — chained content keys, the
  full-blocks-strictly-before-last-token hit cap, idempotent registration,
  evict-hook key erasure.
* **Scheduler + engine** — token identity cache-on vs cache-off (the cache
  may only move WHEN work happens, never what is computed), partial last
  blocks never shared between live requests, preempt-restart remapping
  through the cache with strictly fewer prefill chunks than the cold path,
  and byte-identical schedule replay with the cache enabled.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.block_allocator import AllocationError, BlockAllocator
from deepspeed_tpu.serve.engine import InferenceEngine
from deepspeed_tpu.serve.prefix_cache import PrefixCache
from deepspeed_tpu.serve.scheduler import Request, Scheduler

ML = 32


@pytest.fixture(scope="module")
def model_and_params():
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_and_params, **kw):
    model, params = model_and_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 33)
    kw.setdefault("max_model_len", ML)
    kw.setdefault("prefill_chunk", 8)
    return InferenceEngine(model, params, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 64, size=n).astype(np.int32).tolist()


def _clone(reqs):
    return [Request(r.req_id, list(r.prompt), r.max_new_tokens,
                    arrival=r.arrival, num_beams=r.num_beams) for r in reqs]


# --------------------------------------------------------- allocator tier


def test_lru_eviction_before_refusal():
    """Under pressure the allocator reclaims parked prefixes oldest-first —
    and refuses only once the free list AND the cached tier are both empty."""
    a = BlockAllocator(num_blocks=5, block_size=4)     # 4 usable pages
    blocks = a.allocate(4)
    evicted = []
    a.set_evict_hook(lambda b, k: evicted.append((b, k)))
    for i, b in enumerate(blocks):
        a.register_cached(b, f"key{i}")
    a.free(blocks)                                     # all 4 park, in order
    assert a.num_cached == 4 and a.num_free == 4

    got = a.allocate(3)                                # pure-pressure allocs
    assert evicted == [(blocks[0], "key0"), (blocks[1], "key1"),
                       (blocks[2], "key2")]            # oldest-first LRU
    assert got == blocks[:3]
    assert a.num_cached == 1
    a.allocate(1)                                      # last parked page goes
    assert a.num_cached == 0 and a.num_free == 0
    with pytest.raises(AllocationError):               # only NOW refuse
        a.allocate(1)


def test_revive_touches_lru_order():
    """A hit on a parked page revives it; its next park lands at the newest
    LRU slot, so a revived prefix outlives never-touched ones."""
    a = BlockAllocator(num_blocks=4, block_size=4)
    b1, b2, b3 = a.allocate(3)
    a.register_cached(b1, "k1")
    a.register_cached(b2, "k2")
    a.free([b1, b2])                                   # LRU order: b1, b2
    a.revive(b1)                                       # hit on the older one
    assert not a.is_parked(b1) and a.refcount(b1) == 1
    a.free([b1])                                       # re-park: now newest
    evicted = []
    a.set_evict_hook(lambda b, k: evicted.append(b))
    a.free([b3])                                       # unregistered -> free list
    a.allocate(2)                                      # free list first, then LRU
    assert evicted == [b2]                             # b2 now older than b1
    assert a.cache_revivals == 1 and a.cache_evictions == 1


def test_register_cached_validation():
    a = BlockAllocator(num_blocks=4, block_size=4)
    (b,) = a.allocate(1)
    a.register_cached(b, "k")
    a.register_cached(b, "k")                          # idempotent
    with pytest.raises(ValueError):
        a.register_cached(b, "other")                  # re-keying is a bug
    with pytest.raises(ValueError):
        a.register_cached(99, "k")                     # unallocated
    with pytest.raises(ValueError):
        a.revive(b)                                    # live, not parked


def test_fork_then_evict_refcount_ordering_deterministic():
    """fork -> free -> park -> evict runs byte-identically twice: the same
    counters, the same eviction order, the same free-list state."""
    def run():
        a = BlockAllocator(num_blocks=6, block_size=4)
        order = []
        a.set_evict_hook(lambda b, k: order.append((b, k)))
        t = a.allocate(3)
        for i, b in enumerate(t):
            a.register_cached(b, ("chain", i))
        forked = a.fork(t)                             # refcount 2 everywhere
        a.free(t)                                      # still live via fork
        assert a.num_cached == 0
        a.free(forked)                                 # last ref -> park all 3
        assert a.num_cached == 3
        a.allocate(5)                                  # 2 free + 3 evictions
        return order, a.cache_evictions, a.fork_count, a.num_free

    assert run() == run()
    order, evictions, forks, free = run()
    assert evictions == 3 and forks == 3 and free == 0
    assert [k for _, k in order] == [("chain", 0), ("chain", 1), ("chain", 2)]


def test_unregistered_allocator_paths_unchanged():
    """With no registrations the cached tier is invisible: free pages return
    to the free list and stats read exactly as the pre-cache allocator."""
    a = BlockAllocator(num_blocks=5, block_size=4)
    t = a.allocate(3)
    a.free(t)
    assert a.num_cached == 0 and a.free_count == 3
    assert a.stats()["free"] == 4


# ---------------------------------------------------------- PrefixCache


def test_hit_capped_strictly_before_last_prompt_token():
    """Even a fully-cached prompt must leave its final token to a real
    prefill chunk — its logits seed the first generated token."""
    a = BlockAllocator(num_blocks=9, block_size=4)
    pc = PrefixCache(a, block_size=4)
    prompt = list(range(8))                            # exactly 2 full blocks
    t = a.allocate(2)
    pc.register(prompt, t, known_tokens=8)
    blocks, hit_tokens = pc.peek(prompt)
    assert blocks == t[:1] and hit_tokens == 4         # (8-1)//4 == 1 block
    longer = prompt + [9]
    blocks, hit_tokens = pc.peek(longer)
    assert blocks == t and hit_tokens == 8             # now both blocks safe


def test_chain_keys_distinguish_same_block_different_prefix():
    """Key identity is the whole chain, not the block content: the same
    4 tokens after two different first blocks are two distinct entries."""
    a = BlockAllocator(num_blocks=9, block_size=4)
    pc = PrefixCache(a, block_size=4)
    common = [7, 7, 7, 7]
    p1, p2 = [1, 2, 3, 4] + common, [5, 6, 7, 8] + common
    t1, t2 = a.allocate(2), a.allocate(2)
    pc.register(p1, t1, known_tokens=8)
    pc.register(p2, t2, known_tokens=8)
    assert pc.peek(p1 + [0])[0] == t1
    assert pc.peek(p2 + [0])[0] == t2
    assert pc.peek(common + common + [0])[0] == []     # no such chain


def test_eviction_erases_key_and_misses_afterwards():
    a = BlockAllocator(num_blocks=3, block_size=4)     # 2 usable pages
    pc = PrefixCache(a, block_size=4)
    prompt = list(range(8))
    t = a.allocate(2)
    pc.register(prompt, t, known_tokens=8)
    a.free(t)                                          # both park
    a.allocate(2)                                      # pressure evicts both
    assert pc.peek(prompt + [0]) == ([], 0)
    assert a.cache_evictions == 2 and pc.stats()["parked_blocks"] == 0


# ----------------------------------------------------- scheduler semantics


def _sched(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("num_blocks", 17)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_model_len", 32)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefix_cache", True)
    return Scheduler(**kw)


def _run_prefill(s, g, it=0):
    while g.prefill_done < g.prompt_len:
        n = min(s.prefill_chunk, g.prompt_len - g.prefill_done)
        s.finish_prefill_chunk(g, n, it)


def test_partial_last_block_never_aliased_between_live_requests():
    """Two live requests sharing a 10-token prompt share the two FULL prompt
    blocks (refcount 2) but never the partial third — each owns a private
    page for tokens 8..9 and every decode write past the prompt."""
    s = _sched()
    prompt = list(range(10))                           # 2 full blocks + 2 tokens
    s.submit(Request("a", list(prompt), 8))
    (ga,) = s.admit(0)
    _run_prefill(s, ga)
    s.begin_decode(ga, [1], 0)                         # registers full blocks
    s.submit(Request("b", list(prompt), 8))
    (gb,) = s.admit(1)
    assert gb.cached_prefix_tokens == 8
    assert gb.tables[0][:2] == ga.tables[0][:2]        # shared full blocks
    assert s.allocator.refcount(ga.tables[0][0]) == 2
    assert gb.tables[0][2] != ga.tables[0][2]          # partial block private
    assert s.allocator.refcount(ga.tables[0][2]) == 1
    assert s.allocator.refcount(gb.tables[0][2]) == 1


def test_admission_counts_parked_hit_blocks_as_pinned():
    """A hit on parked pages pins them: admission must not double-count them
    as both 'reused for free' and 'still evictable for the fresh blocks'."""
    s = _sched(num_blocks=7)                           # 6 usable pages
    prompt = list(range(12))                           # 3 blocks
    s.submit(Request("a", list(prompt), 4))
    (ga,) = s.admit(0)
    _run_prefill(s, ga)
    s.begin_decode(ga, [1], 0)
    s.finish_group(ga)                                 # all 3 full blocks park
    assert s.allocator.num_cached == 3
    # b's hit is capped at 2 blocks ((12-1)//4 — the chunk completing the
    # prompt must run), pinning 2 of the 3 parked pages; the fresh blocks
    # come out of the free list without touching the still-parked third
    s.submit(Request("b", list(prompt), 4))
    (gb,) = s.admit(1)
    assert gb.cached_prefix_tokens == 8
    assert s.allocator.refcount(gb.tables[0][0]) == 1  # revived, not copied


def test_scheduler_cache_off_is_bit_identical_baseline():
    """prefix_cache=False constructs no cache and hands out the exact table
    ids the pre-cache scheduler did (pinned by the existing scheduler tests
    continuing to pass — here we just assert the gate is really off)."""
    s = Scheduler(num_slots=4, num_blocks=17, block_size=4, max_model_len=32,
                  prefill_chunk=8)
    assert s.prefix_cache is None


# ------------------------------------------------------- engine end-to-end


def test_cache_on_token_identity_and_fewer_prefill_chunks(model_and_params):
    """Shared-system-prompt trace: cache-on produces the SAME tokens as
    cache-off while scheduling strictly fewer prefill tokens, and the ledger
    classifies the skipped tokens as cached_prefix_tokens."""
    sys_p = _prompt(50, 8)
    reqs = [Request(f"r{i}", sys_p + _prompt(60 + i, 5), 6) for i in range(6)]
    off = _engine(model_and_params, request_trace={"enabled": True})
    outs_off, _ = off.run(_clone(reqs))
    on = _engine(model_and_params, prefix_cache=True,
                 request_trace={"enabled": True})
    outs_on, _ = on.run(_clone(reqs))

    assert [(o.req_id, o.tokens) for o in outs_on] == \
           [(o.req_id, o.tokens) for o in outs_off]
    w_on, w_off = on.tracer.waste_summary(), off.tracer.waste_summary()
    assert w_on["cached_prefix_tokens"] > 0
    assert w_off["cached_prefix_tokens"] == 0
    assert w_on["prefill_tokens"] == \
           w_off["prefill_tokens"] - w_on["cached_prefix_tokens"]
    assert on.prefix_cache.stats()["hits"] > 0


def test_preempt_restart_remaps_through_cache(model_and_params):
    """Satellite contract: a preempted request's restart remaps its prompt
    blocks from the cache instead of re-prefilling. Token-identical to the
    cold path, preemptions actually happened, and the warm engine schedules
    strictly fewer prefill chunks than the cold (cache-off) starved engine."""
    # r0's long generation eats the 8-page pool while r1 (latest admitted,
    # the preemption victim) is mid-flight with a fully prefilled 16-token
    # prompt — its restarts remap 3 of 4 prompt blocks from the cached tier
    reqs = [Request("r0", _prompt(1, 4), 12), Request("r1", _prompt(2, 16), 12)]
    cold = _engine(model_and_params, num_blocks=9,
                   request_trace={"enabled": True})
    outs_cold, _ = cold.run(_clone(reqs))
    warm = _engine(model_and_params, num_blocks=9, prefix_cache=True,
                   request_trace={"enabled": True})
    outs_warm, _ = warm.run(_clone(reqs))
    big = _engine(model_and_params, num_blocks=33)
    outs_big, _ = big.run(_clone(reqs))

    assert sum(o.preemptions for o in outs_warm) > 0
    assert [o.tokens for o in outs_warm] == [o.tokens for o in outs_big]
    assert [o.tokens for o in outs_warm] == [o.tokens for o in outs_cold]

    def prefill_chunks(eng):
        return sum(1 for r in eng.tracer.requests
                   for e in r["events"] if e[0] == "prefill")

    assert prefill_chunks(warm) < prefill_chunks(cold)
    assert warm.tracer.waste_summary()["cached_prefix_tokens"] > 0
    # remapped restarts shrink the replay bill too, never inflate it
    assert (warm.tracer.waste_summary()["replayed_tokens"]
            < cold.tracer.waste_summary()["replayed_tokens"])


def test_replay_byte_identical_with_cache_on(model_and_params):
    """The cache is a pure function of the trace: two fresh engines replay
    the same shared-prefix trace with byte-identical schedule logs."""
    sys_p = _prompt(70, 8)
    reqs = [Request(f"r{i}", sys_p + _prompt(80 + i, 3 + i % 4), 5,
                    arrival=i // 2) for i in range(6)]
    logs = []
    for _ in range(2):
        eng = _engine(model_and_params, prefix_cache=True, num_blocks=17)
        outs, log = eng.run(_clone(reqs))
        logs.append((json.dumps(log),
                     [(o.req_id, o.tokens) for o in outs]))
    assert logs[0] == logs[1]


def test_mirror_forbidden_with_cache(model_and_params):
    """The dense oracle re-prefills everything; a cache hit skips prefill, so
    lockstep is impossible by construction — fail loudly at build time."""
    with pytest.raises(ValueError, match="mirror"):
        _engine(model_and_params, prefix_cache=True, mirror=True)
