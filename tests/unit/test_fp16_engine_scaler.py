"""Engine-level dynamic loss-scaler coverage (CPU tier).

`test_fp16_optimizer.py` pins the FP16_Optimizer wrapper and
`test_engine.py` the single-overflow skip; this suite drives the ENGINE's
in-jit scaler state machine through full ramp/backoff cycles with injected
overflows and checks the three contracts the training loop relies on:

- the dynamic schedule: doubling after ``loss_scale_window`` clean steps,
  hysteresis consumed before halving, ``min_loss_scale`` floor;
- skipped-step accounting: ``skipped_steps`` counts exactly the steps whose
  parameter update was suppressed, ``global_steps`` counts all of them, and the
  scaler's own ``iter_count`` ticks every step;
- recovery: a run that hits overflows ends up on the never-overflowed run's
  loss trajectory once the bad batches pass (a skipped step must not corrupt
  optimizer or master state).
"""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from tests.unit.simple_model import SimpleModel, simple_config

HIDDEN = 16


def _engine(fp16_cfg, seed=0):
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(seed))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config_params=simple_config(batch=8, fp16=fp16_cfg))
    return engine


def _clean_batch(i):
    rng = np.random.default_rng(100 + i)
    x = rng.normal(size=(8, HIDDEN)).astype(np.float32)
    return x, np.tanh(x)


# targets this size overflow the scaled loss/grads for any scale >= 1
_OVERFLOW_BATCH = (np.ones((8, HIDDEN), np.float32),
                   np.full((8, HIDDEN), 1e30, np.float32))


def _step(engine, batch):
    loss = engine(*batch)
    engine.backward(loss)
    engine.step()
    return float(jax.device_get(loss))


def test_dynamic_scale_ramp_and_backoff():
    """Walk the full state machine: window-doubling, hysteresis absorbing the
    first overflow, the halve on the second, and the post-recovery re-ramp.
    (Window math: the scaler doubles when (iter_count - last_overflow_iter) is
    a multiple of the window; last_overflow_iter starts at -1.)"""
    engine = _engine({"enabled": True, "loss_scale": 0, "initial_scale_power": 4,
                      "loss_scale_window": 3, "hysteresis": 2,
                      "min_loss_scale": 1})
    assert engine.loss_scale() == 16.0
    scales = []
    for i in range(5):  # clean ramp: doubles at iter 2 and iter 5
        _step(engine, _clean_batch(i))
        scales.append(engine.loss_scale())
    assert scales == [16.0, 32.0, 32.0, 32.0, 64.0], scales

    _step(engine, _OVERFLOW_BATCH)  # hysteresis 2 -> 1: scale survives
    assert engine.loss_scale() == 64.0
    assert engine.skipped_steps == 1
    _step(engine, _OVERFLOW_BATCH)  # hysteresis exhausted: halve
    assert engine.loss_scale() == 32.0
    assert engine.skipped_steps == 2

    for i in range(3):  # window counts from the overflow iter: re-ramp on the 3rd
        _step(engine, _clean_batch(10 + i))
    assert engine.loss_scale() == 64.0
    assert engine.skipped_steps == 2


def test_dynamic_scale_respects_min_scale_floor():
    engine = _engine({"enabled": True, "loss_scale": 0, "initial_scale_power": 2,
                      "loss_scale_window": 1000, "hysteresis": 1,
                      "min_loss_scale": 2})
    assert engine.loss_scale() == 4.0
    for _ in range(4):  # halves once, then pins at the floor
        _step(engine, _OVERFLOW_BATCH)
    assert engine.loss_scale() == 2.0
    assert engine.skipped_steps == 4


def test_skipped_step_accounting_matches_engine_counters():
    """Every step ticks global_steps and the scaler's iter_count; ONLY the
    overflowed ones tick skipped_steps; and the number of actual parameter
    updates observed equals global_steps - skipped_steps."""
    engine = _engine({"enabled": True, "loss_scale": 0, "initial_scale_power": 4,
                      "loss_scale_window": 1000, "hysteresis": 1,
                      "min_loss_scale": 1})
    overflow_at = {3, 7}
    updates_seen = 0
    for i in range(12):
        before = jax.device_get(engine.master_params)
        batch = _OVERFLOW_BATCH if i in overflow_at else _clean_batch(i)
        _step(engine, batch)
        after = jax.device_get(engine.master_params)
        changed = any(
            not np.array_equal(a, b)
            for a, b in zip(jax.tree_util.tree_leaves(before),
                            jax.tree_util.tree_leaves(after)))
        if i in overflow_at:
            assert not changed, f"overflowed step {i} must not move params"
        else:
            assert changed, f"clean step {i} must move params"
        updates_seen += changed
    assert engine.global_steps == 12
    assert engine.skipped_steps == len(overflow_at)
    assert int(jax.device_get(engine.scaler_state.iter_count)) == 12
    assert updates_seen == engine.global_steps - engine.skipped_steps


def test_post_recovery_trajectory_matches_clean_run():
    """After the bad batches pass, the overflowed run must rejoin the
    never-overflowed run's trajectory exactly: a skipped step leaves master
    params, optimizer state, and the schedule step counter untouched, and the
    (halved) scale cancels out of the fp32 unscale."""
    def run(inject):
        engine = _engine({"enabled": True, "loss_scale": 0,
                          "initial_scale_power": 6, "loss_scale_window": 1000,
                          "hysteresis": 1, "min_loss_scale": 1})
        losses = []
        for i in range(7):
            losses.append(_step(engine, _clean_batch(i)))
        if inject:
            for _ in range(2):
                _step(engine, _OVERFLOW_BATCH)
            assert engine.skipped_steps == 2
            assert engine.loss_scale() == 16.0  # 64 halved twice (hysteresis 1)
        for i in range(7, 14):
            losses.append(_step(engine, _clean_batch(i)))
        return losses, jax.device_get(engine.master_params)

    losses_ref, params_ref = run(inject=False)
    losses_ovf, params_ovf = run(inject=True)
    # the recovery run saw 2 extra (overflowed) steps; drop them for comparison
    np.testing.assert_allclose(losses_ovf[:7], losses_ref[:7], rtol=1e-6)
    np.testing.assert_allclose(losses_ovf[7:], losses_ref[7:], rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        params_ovf, params_ref)
