"""AST lint passes: host-sync, tracer-hostile, recompile-hazard, config-keys,
plus the violation/allowlist/report model they all share.

Synthetic-module tests write small files to tmp_path and assert each pass
fires exactly where it should (and nowhere else — scoping to the jitted
closure is the part that rots). Repo-level tests pin the live baseline:
the whole package must stay clean modulo the shipped allowlist.
"""

import json
import os
import textwrap

import pytest

import deepspeed_tpu
from deepspeed_tpu.lint.ast_passes import (HostSyncPass, RecompileHazardPass,
                                           TracerHostilePass, run_ast_passes)
from deepspeed_tpu.lint.config_pass import ConfigKeysPass, declared_key_constants
from deepspeed_tpu.lint.model import Allowlist, LintReport, Violation

PKG = os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))


def _write(tmp_path, name, source):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return str(p)


# ------------------------------------------------------------------ host-sync
def test_host_sync_pass_flags_all_three_primitives(tmp_path):
    f = _write(tmp_path, "mod.py", """
        import jax
        import numpy as np

        def fetch(x):
            host = jax.device_get(x)
            x.block_until_ready()
            return np.asarray(x)
    """)
    rules = sorted(v.rule for v in run_ast_passes([f], (HostSyncPass(),),
                                                  root=str(tmp_path)))
    assert rules == ["block-until-ready", "device-get", "np-asarray"]


def test_host_sync_subjects_are_repo_relative_qualnames(tmp_path):
    f = _write(tmp_path, "mod.py", """
        import jax

        class Session:
            def end(self, x):
                return jax.device_get(x)
    """)
    (v,) = run_ast_passes([f], (HostSyncPass(),), root=str(tmp_path))
    assert v.vid == "ast-host-sync:device-get:mod.py::Session.end"


# ------------------------------------------------------------- tracer-hostile
def test_tracer_hostile_only_fires_inside_jitted_closure(tmp_path):
    f = _write(tmp_path, "mod.py", """
        import jax

        @jax.jit
        def step(x):
            return helper(x)

        def helper(x):
            return float(x)        # reached from a jit root -> flagged

        def host_only(x):
            return float(x)        # never jitted -> fine
    """)
    vs = run_ast_passes([f], (TracerHostilePass(),), root=str(tmp_path))
    assert [v.vid for v in vs] == ["ast-tracer-hostile:host-cast:mod.py::helper"]


def test_tracer_hostile_sees_jit_call_sites_and_item(tmp_path):
    f = _write(tmp_path, "mod.py", """
        import jax

        def compiled(x):
            return x.item()

        run = jax.jit(compiled)
    """)
    vs = run_ast_passes([f], (TracerHostilePass(),), root=str(tmp_path))
    assert [v.vid for v in vs] == ["ast-tracer-hostile:item-call:mod.py::compiled"]


def test_tracer_hostile_ignores_literal_casts(tmp_path):
    f = _write(tmp_path, "mod.py", """
        import jax

        @jax.jit
        def step(x):
            return x * int("42")   # constant-arg cast: concrete at trace time
    """)
    assert run_ast_passes([f], (TracerHostilePass(),), root=str(tmp_path)) == []


# ------------------------------------------------------------ recompile-hazard
def test_recompile_hazard_flags_time_in_traced_code(tmp_path):
    f = _write(tmp_path, "mod.py", """
        import jax
        import time

        @jax.jit
        def step(x):
            return x + time.time()
    """)
    vs = run_ast_passes([f], (RecompileHazardPass(),), root=str(tmp_path))
    assert [v.rule for v in vs] == ["nondeterminism-in-trace"]


def test_recompile_hazard_flags_unhashable_static_default(tmp_path):
    f = _write(tmp_path, "mod.py", """
        import jax
        from functools import partial

        def step(x, cfg=[1, 2]):
            return x

        run = jax.jit(step, static_argnums=(1,))
    """)
    vs = run_ast_passes([f], (RecompileHazardPass(),), root=str(tmp_path))
    assert [v.rule for v in vs] == ["unhashable-static"]
    assert vs[0].subject.endswith("::step#cfg")


# ------------------------------------------------------------------ config keys
def test_every_declared_config_key_is_reachable():
    """Satellite check: every NAME/NAME_DEFAULT pair in runtime/constants.py
    must be referenced from a config-consuming module — a key users can set
    that nothing reads is the silent no-op the sweep exists to prevent."""
    vs = ConfigKeysPass(PKG).run()
    assert vs == [], "\n".join(v.message for v in vs)


def test_declared_key_constants_sees_the_real_registry():
    keys = declared_key_constants(os.path.join(PKG, "runtime", "constants.py"))
    assert "TRAIN_BATCH_SIZE" in keys and keys["TRAIN_BATCH_SIZE"] == "train_batch_size"
    assert "NUMERICS_RING_SIZE" in keys
    # paired _DEFAULT is what marks a config key; bare strings don't count
    assert "TELEMETRY" not in keys  # block name, no TELEMETRY_DEFAULT


# ------------------------------------------------------------- repo baseline
def test_package_ast_baseline_is_clean_modulo_shipped_allowlist():
    """The live repo, exactly as `ds-tpu lint` sees it: zero non-allowlisted
    AST violations, zero stale allowlist entries."""
    from deepspeed_tpu.lint.cli import _DEFAULT_ALLOWLIST, run_ast_surface
    allowlist = Allowlist.load(_DEFAULT_ALLOWLIST)
    report = LintReport()
    run_ast_surface(report, allowlist, package_dir=PKG)
    report.finish(allowlist)
    assert report.violations == [], "\n".join(v.vid for v in report.violations)
    assert report.unused_allow == []


# ------------------------------------------------------------ model semantics
def test_violation_id_and_dict_shape():
    v = Violation("p", "r", "s", "msg", details={"n": 1})
    assert v.vid == "p:r:s"
    d = v.to_dict()
    assert d["id"] == "p:r:s" and d["details"] == {"n": 1}


def test_allowlist_requires_reason_and_tracks_unused():
    with pytest.raises(ValueError):
        Allowlist([{"id": "a:*"}])
    al = Allowlist([{"id": "a:*", "reason": "x"}, {"id": "b:*", "reason": "y"}])
    assert al.match("a:r:s") is not None
    assert al.match("c:r:s") is None
    assert al.unused() == ["b:*"]


def test_report_json_is_sorted_and_stable():
    def build(order):
        r = LintReport()
        for s in order:
            r.add(Violation("p", "r", s, f"msg {s}"))
        r.passes = ["z", "a"]
        r.finish()
        return r.to_json()

    a = build(["s2", "s1", "s3"])
    b = build(["s1", "s3", "s2"])
    assert a == b
    parsed = json.loads(a)
    subjects = [v["subject"] for v in parsed["violations"]]
    assert subjects == sorted(subjects)
    assert parsed["passes"] == ["a", "z"]
    assert parsed["summary"]["failed"] is True
