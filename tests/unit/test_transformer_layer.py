"""Fused transformer layer parity tests (mirrors reference test_cuda_forward/backward:
DeepSpeedTransformerLayer vs an independently-written HF-style BertLayer)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer import DeepSpeedTransformerConfig, DeepSpeedTransformerLayer


def hf_style_bert_layer(params, x, heads, pre_ln=False):
    """Independent reference: vanilla post-LN (or pre-LN) BERT encoder layer in plain jax."""

    def ln(x, s, b):
        m = jnp.mean(x, -1, keepdims=True)
        v = jnp.var(x, -1, keepdims=True)
        return (x - m) / jnp.sqrt(v + 1e-12) * s + b

    B, T, H = x.shape
    d = H // heads
    src = ln(x, params["attn_nw"], params["attn_nb"]) if pre_ln else x
    qkv = src @ params["attn_qkvw"] + params["attn_qkvb"]
    q, k, v = jnp.split(qkv, 3, -1)
    q = q.reshape(B, T, heads, d).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, heads, d).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, heads, d).transpose(0, 2, 1, 3)
    scores = (q @ k.transpose(0, 1, 3, 2)) / math.sqrt(d)
    probs = jax.nn.softmax(scores, -1)
    ctx = (probs @ v).transpose(0, 2, 1, 3).reshape(B, T, H)
    attn = ctx @ params["attn_ow"] + params["attn_ob"]
    x = x + attn
    if not pre_ln:
        x = ln(x, params["attn_nw"], params["attn_nb"])
    src = ln(x, params["norm_w"], params["norm_b"]) if pre_ln else x
    h = jax.nn.gelu(src @ params["inter_w"] + params["inter_b"], approximate=False)
    out = h @ params["output_w"] + params["output_b"]
    x = x + out
    if not pre_ln:
        x = ln(x, params["norm_w"], params["norm_b"])
    return x


@pytest.mark.parametrize("batch,seq,hidden,heads", [(2, 64, 64, 4), (3, 128, 128, 8)])
@pytest.mark.parametrize("pre_ln", [False, True])
def test_layer_forward_parity(batch, seq, hidden, heads, pre_ln):
    cfg = DeepSpeedTransformerConfig(batch_size=batch, max_seq_length=seq, hidden_size=hidden,
                                     heads=heads, attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
                                     num_hidden_layers=2, initializer_range=0.02,
                                     pre_layer_norm=pre_ln, bf16=False,
                                     use_flash_attention=False)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, seq, hidden), jnp.float32)
    out_ds = layer.apply(params, x)
    out_ref = hf_style_bert_layer(params, x, heads, pre_ln=pre_ln)
    np.testing.assert_allclose(np.asarray(out_ds), np.asarray(out_ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("pre_ln", [False, True])
def test_layer_backward_parity(pre_ln):
    batch, seq, hidden, heads = 2, 64, 64, 4
    cfg = DeepSpeedTransformerConfig(hidden_size=hidden, heads=heads, attn_dropout_ratio=0.0,
                                     hidden_dropout_ratio=0.0, num_hidden_layers=2,
                                     initializer_range=0.02, pre_layer_norm=pre_ln, bf16=False,
                                     use_flash_attention=False)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, seq, hidden), jnp.float32)

    g_ds = jax.grad(lambda p: jnp.sum(layer.apply(p, x)**2))(params)
    g_ref = jax.grad(lambda p: jnp.sum(hf_style_bert_layer(p, x, heads, pre_ln=pre_ln)**2))(params)
    for k in g_ds:
        np.testing.assert_allclose(np.asarray(g_ds[k]), np.asarray(g_ref[k]),
                                   rtol=5e-4, atol=5e-4, err_msg=k)


def test_memory_knobs_preserve_numerics():
    """normalize_invertible / gelu_checkpoint / attn_dropout_checkpoint change memory,
    never math (reference transformer.py:104-132)."""
    base_kw = dict(hidden_size=64, heads=4, attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
                   num_hidden_layers=2, initializer_range=0.02, bf16=False,
                   use_flash_attention=False)
    layer0 = DeepSpeedTransformerLayer(DeepSpeedTransformerConfig(**base_kw))
    params = layer0.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64), jnp.float32)
    ref = layer0.apply(params, x)
    gref = jax.grad(lambda p: jnp.sum(layer0.apply(p, x)**2))(params)
    for knob in ["normalize_invertible", "gelu_checkpoint", "attn_dropout_checkpoint"]:
        layer = DeepSpeedTransformerLayer(DeepSpeedTransformerConfig(**base_kw, **{knob: True}))
        out = layer.apply(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, err_msg=knob)
        g = jax.grad(lambda p: jnp.sum(layer.apply(p, x)**2))(params)
        for k in g:
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(gref[k]), rtol=1e-5,
                                       atol=1e-6, err_msg=f"{knob}/{k}")


def test_dropout_determinism_with_rng():
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=4, attn_dropout_ratio=0.1,
                                     hidden_dropout_ratio=0.1, num_hidden_layers=2,
                                     initializer_range=0.02, bf16=False, use_flash_attention=False)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64), jnp.float32)
    rng = jax.random.PRNGKey(7)
    a = layer.apply(params, x, rng=rng, deterministic=False)
    b = layer.apply(params, x, rng=rng, deterministic=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = layer.apply(params, x, rng=jax.random.PRNGKey(8), deterministic=False)
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_attention_mask():
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=4, attn_dropout_ratio=0.0,
                                     hidden_dropout_ratio=0.0, num_hidden_layers=2,
                                     initializer_range=0.02, bf16=False, use_flash_attention=False)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64), jnp.float32)
    # mask out the second half of the keys; outputs for the first half should change
    mask = jnp.zeros((2, 1, 1, 64)).at[:, :, :, 32:].set(-1e9)
    out_masked = layer.apply(params, x, attention_mask=mask)
    out_full = layer.apply(params, x)
    assert not np.allclose(np.asarray(out_masked), np.asarray(out_full))


def test_bert_model_mlm_trains():
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
    import deepspeed_tpu
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
                     intermediate_size=64, max_position_embeddings=64,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                     compute_dtype=jnp.float32, use_flash_attention=False)
    model = BertForMaskedLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(8, 16)).astype(np.int32)
    labels = np.where(rng.random((8, 16)) < 0.15, ids, -100).astype(np.int32)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config_params={"train_batch_size": 8, "steps_per_print": 100,
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    losses = []
    for _ in range(10):
        loss = engine(ids, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]
