"""CIFAR ResNet workload through the engine (the reference's DeepSpeedExamples/cifar
config, BASELINE.json) — proves the engine is model-agnostic beyond transformers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.resnet import ResNet, ResNetConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.mesh import build_mesh


def _data(batch=8, classes=10, seed=0):
    """Learnable synthetic CIFAR: class k images have channel means biased by k."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, (batch,)).astype(np.int32)
    images = rng.normal(size=(batch, 16, 16, 3)).astype(np.float32) * 0.3
    images += (labels[:, None, None, None] / classes - 0.5) * 2.0
    return images, labels


@pytest.mark.parametrize("zero_stage", [0, 2])
def test_cifar_resnet_trains(zero_stage, eight_devices):
    model = ResNet(ResNetConfig(width=8, stage_sizes=(1, 1), groups=4))
    params = model.init(jax.random.PRNGKey(0))
    engine = DeepSpeedEngine(
        model=model, model_parameters=params,
        mesh=build_mesh(data=8, model=1, pipe=1),
        config_params={"train_batch_size": 8, "steps_per_print": 100,
                       "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                       "zero_optimization": {"stage": zero_stage}})
    images, labels = _data()
    losses = []
    for _ in range(6):
        loss = engine(images, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], f"CIFAR loss did not decrease: {losses}"


def test_resnet_logits_shape_and_downsampling():
    model = ResNet(ResNetConfig(width=8, stage_sizes=(1, 1, 1), groups=4))
    params = model.init(jax.random.PRNGKey(1))
    logits = model.logits(params, jnp.zeros((2, 32, 32, 3)))
    assert logits.shape == (2, 10)
    assert model.param_count(params) > 0
