"""InferenceEngine end-to-end equivalence and contracts.

The serving path must be a scheduling/memory-layout change, not a numerics
change: a request served through continuous batching + paged KV produces the
SAME tokens as the model's own monolithic ``generate`` / ``beam_search``
(which decode one request at fixed [B, K] shapes with contiguous caches).
Also pinned: zero decode-program recompiles after warmup (the compile
watchdog), Serving/* scalars through TelemetrySession, preempt-and-restart
transparency, and admission refusal instead of OOM crashes.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.engine import InferenceEngine
from deepspeed_tpu.serve.scheduler import Request
from deepspeed_tpu.serve.sim import synth_trace
from deepspeed_tpu.utils.telemetry import TelemetrySession

ML = 32


@pytest.fixture(scope="module")
def model_and_params():
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_and_params, **kw):
    model, params = model_and_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 33)
    kw.setdefault("max_model_len", ML)
    kw.setdefault("prefill_chunk", 8)
    return InferenceEngine(model, params, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 64, size=n).astype(np.int32).tolist()


def test_greedy_matches_model_generate(model_and_params):
    model, params = model_and_params
    prompt = _prompt(0, 11)
    L = 7
    eng = _engine(model_and_params, mirror=True)
    outs, _ = eng.run([Request("r0", prompt, L)])
    assert outs[0].status == "finished"

    ref = model.generate(params, jnp.asarray([prompt], jnp.int32), L)
    ref_new = np.asarray(ref)[0, len(prompt):].tolist()
    assert outs[0].tokens == ref_new
    assert eng.mirror_checks > 0


@pytest.mark.parametrize("eos", [None, 5])
def test_beam4_matches_model_beam_search(model_and_params, eos):
    model, params = model_and_params
    T0, L, K = 8, 6, 4
    prompt = _prompt(1, T0)
    # prefill_chunk == T0 and num_slots == K: the one shape regime where the
    # monolithic beam_search and the slot-per-beam engine take identical-shape
    # device steps, so tokens AND the final GNMT score agree exactly
    eng = _engine(model_and_params, num_slots=K, prefill_chunk=T0, mirror=True)
    outs, _ = eng.run([Request("b0", prompt, L, num_beams=K,
                               eos_token_id=eos)])
    assert outs[0].status == "finished"

    seqs, scores = model.beam_search(params, jnp.asarray([prompt], jnp.int32),
                                     L, K, eos_token_id=eos,
                                     length_penalty=1.0)
    ref_new = np.asarray(seqs)[0, T0:].tolist()
    assert outs[0].tokens == ref_new
    # scores accumulate per-step log-probs in different jit programs (the
    # engine's beam_select head vs beam_search's scan body) — ulp drift in the
    # running sum is expected; the RANKING (hence tokens) must still agree
    assert outs[0].score == pytest.approx(float(np.asarray(scores)[0]),
                                          rel=1e-5)
    assert eng.mirror_checks > 0


def test_infeasible_request_is_refused_not_crashed(model_and_params):
    eng = _engine(model_and_params)
    outs, _ = eng.run([
        Request("ok", _prompt(2, 6), 4),
        Request("too-long", _prompt(3, 20), ML, arrival=0),  # 20 + 32 > ML
    ])
    by_id = {o.req_id: o for o in outs}
    assert by_id["ok"].status == "finished"
    assert by_id["too-long"].status == "refused"
    assert by_id["too-long"].refusal            # reason string, not a crash


def test_zero_recompiles_and_serving_scalars(model_and_params, tmp_path):
    session = TelemetrySession(output_path=str(tmp_path), job_name="serve")
    eng = _engine(model_and_params, telemetry=session, mirror=True)
    reqs = synth_trace(10, vocab_size=64, max_model_len=ML, seed=3)
    outs, _ = eng.run(reqs)
    assert all(o.status == "finished" for o in outs)
    assert eng.mirror_checks > 0

    served = [n for n in session.watchdog.records if n.startswith("serve:")]
    assert "serve:decode_step" in served
    assert "serve:prefill_chunk" in served
    for name in served:
        assert session.watchdog.compiles(name) == 1, name
        assert session.watchdog.recompiles(name) == 0, name

    session.monitor.close()
    path = os.path.join(session.monitor.log_dir, "scalars.jsonl")
    tags = {json.loads(line)["tag"] for line in open(path)}
    for tag in ("Serving/occupancy", "Serving/free_blocks", "Serving/waiting",
                "Serving/tok_s", "Serving/goodput_tok_s", "Serving/ttft_ms",
                "Serving/ttft_iters"):
        assert tag in tags, tag


def test_preemption_restores_identical_tokens(model_and_params):
    """Starve the pool so requests get preempted (full-restart recompute) —
    outputs must equal an un-starved engine's exactly, with the preemption
    visible in the output metadata. mirror=True keeps the bitwise oracle
    assertion live THROUGH the restarts."""
    reqs = [Request(f"r{i}", _prompt(10 + i, 9), 6) for i in range(4)]
    small = _engine(model_and_params, num_blocks=13, mirror=True)
    outs_small, _ = small.run([Request(r.req_id, list(r.prompt),
                                       r.max_new_tokens) for r in reqs])
    big = _engine(model_and_params, num_blocks=33)
    outs_big, _ = big.run([Request(r.req_id, list(r.prompt),
                                   r.max_new_tokens) for r in reqs])

    assert sum(o.preemptions for o in outs_small) > 0
    assert [o.tokens for o in outs_small] == [o.tokens for o in outs_big]
    assert small.mirror_checks > 0


def test_sampling_temperature_zero_equals_greedy(model_and_params):
    """The sampled lane must degenerate to the exact greedy path: temperature=0
    short-circuits to np.argmax, temperature->0 concentrates the softmax onto
    the argmax token, and top_k=1 truncates to it — all three byte-identical to
    the default request, whatever the seed."""
    prompt, L = _prompt(30, 9), 6
    base = _engine(model_and_params).run([Request("g", prompt, L)])[0][0]
    for kw in ({"temperature": 0.0, "top_k": 7, "top_p": 0.8, "seed": 99},
               {"temperature": 1e-6, "seed": 4},
               {"temperature": 2.0, "top_k": 1, "seed": 5}):
        out = _engine(model_and_params).run(
            [Request("s", prompt, L, **kw)])[0][0]
        assert out.tokens == base.tokens, kw


def test_sampling_seeded_replay_and_seed_sensitivity(model_and_params):
    """Counter-based draws: the same (seed, trace) replays byte-identically in
    a fresh engine; different seeds explore different continuations."""
    prompt, L = _prompt(31, 8), 8
    kw = dict(temperature=1.5, top_p=0.95, seed=7)
    a = _engine(model_and_params).run([Request("s", prompt, L, **kw)])[0][0]
    b = _engine(model_and_params).run([Request("s", prompt, L, **kw)])[0][0]
    assert a.tokens == b.tokens
    others = [_engine(model_and_params).run(
        [Request("s", prompt, L, temperature=1.5, top_p=0.95, seed=s)]
    )[0][0].tokens for s in (8, 9, 10)]
    assert any(t != a.tokens for t in others), \
        "three different seeds all reproduced the same 8-token continuation"


def test_sampling_survives_preemption(model_and_params):
    """Preemption restarts recompute bit-identical logits and the counter-based
    RNG is keyed on (seed, position) with no mutable state, so a starved engine
    resamples exactly the tokens an un-starved one drew."""
    reqs = [dict(req_id=f"r{i}", prompt=_prompt(40 + i, 9), max_new_tokens=6,
                 temperature=1.2, top_k=16, seed=100 + i) for i in range(4)]
    def mk(r):
        return Request(r["req_id"], list(r["prompt"]), r["max_new_tokens"],
                       temperature=r["temperature"], top_k=r["top_k"],
                       seed=r["seed"])
    outs_small, _ = _engine(model_and_params, num_blocks=13).run(
        [mk(r) for r in reqs])
    outs_big, _ = _engine(model_and_params, num_blocks=33).run(
        [mk(r) for r in reqs])
    assert sum(o.preemptions for o in outs_small) > 0
    assert [o.tokens for o in outs_small] == [o.tokens for o in outs_big]


def test_sampling_request_validation():
    with pytest.raises(ValueError):
        Request("x", [1, 2], 4, temperature=-0.5)
    with pytest.raises(ValueError):
        Request("x", [1, 2], 4, top_p=0.0)
    with pytest.raises(ValueError):
        Request("x", [1, 2], 4, top_k=-1)
    with pytest.raises(ValueError):
        Request("x", [1, 2], 4, temperature=0.7, num_beams=4)


def test_config_facade_init_inference(model_and_params):
    """deepspeed_tpu.init_inference wires the "serving" config block through
    DeepSpeedConfig into a working engine."""
    import deepspeed_tpu

    model, params = model_and_params
    eng = deepspeed_tpu.init_inference(
        model=model, model_parameters=params,
        config_params={"serving": {"enabled": True, "block_size": 4,
                                   "num_blocks": 33, "max_seqs": 4,
                                   "max_model_len": ML, "prefill_chunk": 8}})
    assert eng.block_size == 4 and eng.num_slots == 4
    outs, _ = eng.run([Request("c0", _prompt(20, 5), 3)])
    assert outs[0].status == "finished" and len(outs[0].tokens) == 3
