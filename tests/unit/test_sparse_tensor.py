"""Row-sparse gradient tests (reference had no csr_tensor unit tests; the engine CSR
allreduce at engine.py:1091-1147 is covered here by numeric parity vs dense psum)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import DATA_AXIS, build_mesh, set_mesh, shard_map
from deepspeed_tpu.runtime.sparse_tensor import (SparseTensor, match_sparse_paths,
                                                 row_sparse_allreduce)


def _row_sparse(rows=32, cols=8, nnz=5, seed=0):
    rng = np.random.default_rng(seed)
    dense = np.zeros((rows, cols), np.float32)
    idx = rng.choice(rows, nnz, replace=False)
    dense[idx] = rng.normal(size=(nnz, cols)).astype(np.float32)
    return jnp.asarray(dense)


def test_from_dense_to_dense_roundtrip():
    dense = _row_sparse()
    st = SparseTensor.from_dense(dense, capacity=8)
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense))


def test_from_dense_exact_capacity():
    dense = _row_sparse(nnz=6)
    st = SparseTensor.from_dense(dense, capacity=6)
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense))


def test_from_dense_full_capacity_default():
    dense = _row_sparse()
    st = SparseTensor.from_dense(dense)
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense))


def test_row_zero_nonzero_kept():
    """row 0 nonzero + fill_value=0 slots must not double-count row 0."""
    dense = jnp.zeros((8, 4)).at[0].set(1.0).at[3].set(2.0)
    st = SparseTensor.from_dense(dense, capacity=6)
    np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense))


def test_add_concatenates_and_accumulates():
    a = SparseTensor.from_dense(_row_sparse(seed=1), capacity=8)
    b = SparseTensor.from_dense(_row_sparse(seed=2), capacity=8)
    merged = a.add(b)
    expected = np.asarray(a.to_dense()) + np.asarray(b.to_dense())
    np.testing.assert_allclose(np.asarray(merged.to_dense()), expected)


def test_sparse_size():
    st = SparseTensor.from_dense(_row_sparse(rows=64, cols=16), capacity=4)
    sparse, dense = st.sparse_size()
    assert sparse == 4 + 4 * 16
    assert dense == 64 * 16


def test_jit_friendly():
    """from_dense/to_dense must trace with static shapes."""
    f = jax.jit(lambda d: SparseTensor.from_dense(d, capacity=8).to_dense())
    dense = _row_sparse()
    np.testing.assert_allclose(np.asarray(f(dense)), np.asarray(dense))


def test_match_sparse_paths():
    assert match_sparse_paths("embeddings/word", ("embeddings/word",))
    assert not match_sparse_paths("h/0/attn/w", ("embeddings",))


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs multi-device mesh")
def test_row_sparse_allreduce_matches_pmean():
    mesh = build_mesh(model=1, pipe=1)
    world = mesh.shape[DATA_AXIS]
    rows, cols, k = 64, 8, 6
    per_shard = [np.asarray(_row_sparse(rows, cols, nnz=k, seed=s)) for s in range(world)]
    stacked = jnp.asarray(np.stack(per_shard))  # [world, rows, cols]

    def local(x):
        return row_sparse_allreduce(x[0], DATA_AXIS, capacity=k)

    with set_mesh(mesh):
        out = jax.jit(shard_map(local, mesh=mesh, in_specs=P(DATA_AXIS),
                                out_specs=P(), check_vma=False))(stacked)
    expected = np.mean(np.stack(per_shard), axis=0)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


class _UntiedEmbedModel:
    """Tiny classifier with an UNTIED embedding table: its grad is row-sparse
    (the tied GPT-2/BERT tables get dense LM-head grads, so they don't qualify)."""

    def __init__(self, vocab=64, width=16):
        self.vocab, self.width = vocab, width

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"embed": {"table": jax.random.normal(k1, (self.vocab, self.width)) * 0.02},
                "head": {"w": jax.random.normal(k2, (self.width, 4)) * 0.02}}

    def apply(self, params, tokens, labels):
        x = params["embed"]["table"][tokens].mean(axis=1)  # [B, width]
        logits = x @ params["head"]["w"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))

    def sparse_grad_paths(self):
        return ("embed/table",)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs multi-device mesh")
@pytest.mark.parametrize("zero_stage", [0, 2])
def test_engine_sparse_gradients_parity(zero_stage):
    """Training with sparse_gradients=true must match dense reduction step-for-step."""
    model = _UntiedEmbedModel()
    rng = np.random.default_rng(0)
    batch = (jnp.asarray(rng.integers(0, 64, (8, 12))), jnp.asarray(rng.integers(0, 4, (8,))))

    results = {}
    for sparse in (False, True):
        cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 8 // len(jax.devices()),
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "sparse_gradients": sparse,
               "zero_optimization": {"stage": zero_stage}}
        # the engine takes ownership of (and may donate) the param buffers → fresh init
        params = model.init(jax.random.PRNGKey(0))
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                   config_params=cfg)
        if sparse:
            assert engine._sparse_grad_flags is not None
            assert sum(jax.tree_util.tree_leaves(engine._sparse_grad_flags)) == 1
        for _ in range(3):
            loss = engine.forward(*batch)
            engine.backward(loss)
            engine.step()
        results[sparse] = jax.device_get(engine.master_params)

    # dense path differentiates over the global batch, sparse path over local shards
    # + pmean — same math, different fp32 reduction order, so allow ~1e-4 drift.
    # jax.experimental.shard_map (pre-0.5) lowers the pmean with a different
    # reduction tree and 3 Adam steps amplify the ulps to a few e-4.
    atol = 1e-4 if hasattr(jax, "shard_map") else 5e-4
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=atol),
        results[False], results[True])


class _LabelsFirstModel(_UntiedEmbedModel):
    """Token ids are the SECOND positional input: without the sparse_grad_tokens()
    hint the engine would size the sparse row capacity from the labels tensor."""

    def __init__(self):
        super().__init__(vocab=512)  # big table so the sparse gather path is taken

    def apply(self, params, labels, tokens):
        return super().apply(params, tokens, labels)

    def sparse_grad_tokens(self, labels, tokens):
        return int(np.prod(tokens.shape))


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs multi-device mesh")
def test_engine_sparse_gradients_tokens_hint():
    """Capacity comes from the model's sparse_grad_tokens() hint, not batch arg 0."""
    model = _LabelsFirstModel()
    rng = np.random.default_rng(0)
    # labels-first batch: arg 0 has 8 elements, the token tensor has 8*12
    batch = (jnp.asarray(rng.integers(0, 4, (8,))), jnp.asarray(rng.integers(0, 512, (8, 12))))

    results = {}
    for sparse in (False, True):
        cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 8 // len(jax.devices()),
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "sparse_gradients": sparse,
               "zero_optimization": {"stage": 0}}
        params = model.init(jax.random.PRNGKey(0))
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                   config_params=cfg)
        for _ in range(3):
            loss = engine.forward(*batch)
            engine.backward(loss)
            engine.step()
        if sparse:  # the hint sizes capacity below the table height -> sparse gather
            assert engine._sparse_tokens_fn is not None
        results[sparse] = jax.device_get(engine.master_params)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3, atol=5e-4),
        results[False], results[True])
