"""Static no-host-sync guard for the observability tier (utils/).

The telemetry, numerics and pipeline-trace subsystems promise to add NO host
synchronization to the training step beyond the loss fetch the engine already
performs. That promise is easy to erode one innocent-looking ``device_get``
at a time, so this test enforces it STATICALLY — and since PR 6 it is a thin
wrapper over the lint framework's :class:`HostSyncPass` (the same pass
``ds-tpu lint`` runs), pinned to the same shipped allowlist, so the guard and
the linter cannot drift. Coverage is ALL of ``deepspeed_tpu/utils/`` plus the
serving request-trace ledger (``serve/request_trace.py``), matching the lint
CLI's host-sync surface exactly.
"""

import os

import deepspeed_tpu
from deepspeed_tpu.lint.ast_passes import HostSyncPass, run_ast_passes
from deepspeed_tpu.lint.model import Allowlist

PKG = os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))
ROOT = os.path.dirname(PKG)
UTILS = os.path.join(PKG, "utils")

# the complete sanctioned set — identical to deepspeed_tpu/lint/allowlist.json
PINNED = {
    "ast-host-sync:device-get:deepspeed_tpu/utils/telemetry.py::TelemetrySession.end_step",
    "ast-host-sync:np-asarray:deepspeed_tpu/utils/telemetry.py::_abstract_signature",
}


def _utils_files():
    out = []
    for dirpath, _dirs, files in os.walk(UTILS):
        out += [os.path.join(dirpath, f) for f in files if f.endswith(".py")]
    assert len(out) >= 8, "utils/ sweep looks truncated"
    out.append(os.path.join(PKG, "serve", "request_trace.py"))
    return sorted(out)


def _scan():
    return run_ast_passes(_utils_files(), (HostSyncPass(),), root=ROOT)


def test_utils_sync_allowlist_is_exact():
    """Every host-sync primitive in utils/ must be one of the two sanctioned
    occurrences; anything new is a failure, not a code-review hope."""
    vids = {v.vid for v in _scan()}
    assert vids <= PINNED, f"new host-sync primitive introduced: {vids - PINNED}"
    # the sanctioned fetch must still exist (the scan itself stays honest)
    assert ("ast-host-sync:device-get:deepspeed_tpu/utils/telemetry.py"
            "::TelemetrySession.end_step") in vids


def test_guard_agrees_with_shipped_allowlist():
    """The CLI's allowlist.json and this guard pin the SAME facts: every
    host-sync vid found in utils/ must be covered by the shipped allowlist,
    and the shipped host-sync entries must all still match something."""
    allow = Allowlist.load(os.path.join(PKG, "lint", "allowlist.json"))
    for v in _scan():
        assert allow.match(v.vid) is not None, f"not in shipped allowlist: {v.vid}"
    stale = [g for g in allow.unused() if g.startswith("ast-host-sync:")]
    assert stale == [], f"stale host-sync allowlist entries: {stale}"


def test_pass_reports_occurrence_counts():
    """end_step holds two sanctioned fetch sites; the pass dedupes to one
    violation per (rule, subject) and carries the count in details."""
    by_vid = {v.vid: v for v in _scan()}
    v = by_vid["ast-host-sync:device-get:deepspeed_tpu/utils/telemetry.py"
               "::TelemetrySession.end_step"]
    assert v.details["occurrences"] >= 1


def test_guard_scans_the_real_files():
    files = _utils_files()
    for name in ("telemetry.py", "numerics.py", "pipeline_trace.py", "hlo.py",
                 "profile_ingest.py", "metrics.py", "alerts.py",
                 os.path.join("serve", "request_trace.py")):
        assert any(f.endswith(name) for f in files), f"{name} missing from sweep"


def test_profile_ingest_is_sync_free():
    """The trace ingester runs inside end_step right after a window closes —
    it must stay pure host file parsing: zero host-sync primitives."""
    pi = os.path.join(UTILS, "profile_ingest.py")
    vids = {v.vid for v in run_ast_passes([pi], (HostSyncPass(),), root=ROOT)}
    assert vids == set(), f"host-sync primitive in profile_ingest: {vids}"


def test_metric_catalog_is_sync_free():
    """The metric catalog routes EVERY add_scalar call on every rank — any
    host-sync primitive here would tax every scalar emission in the step
    loop: zero tolerance."""
    m = os.path.join(UTILS, "metrics.py")
    vids = {v.vid for v in run_ast_passes([m], (HostSyncPass(),), root=ROOT)}
    assert vids == set(), f"host-sync primitive in the metric catalog: {vids}"


def test_alert_engine_is_sync_free():
    """The alert engine evaluates on the end_step boundary over the host-side
    metric ring — it must never reach back to the device: zero host-sync
    primitives."""
    a = os.path.join(UTILS, "alerts.py")
    vids = {v.vid for v in run_ast_passes([a], (HostSyncPass(),), root=ROOT)}
    assert vids == set(), f"host-sync primitive in the alert engine: {vids}"


def test_request_trace_ledger_is_sync_free():
    """The serving request tracer sits INSIDE the decode loop, so unlike
    end_step it gets no sanctioned fetch at all: zero host-sync primitives."""
    rt = os.path.join(PKG, "serve", "request_trace.py")
    vids = {v.vid for v in run_ast_passes([rt], (HostSyncPass(),), root=ROOT)}
    assert vids == set(), f"host-sync primitive in the request ledger: {vids}"
