"""Static no-host-sync guard for the observability modules.

The telemetry and numerics subsystems promise to add NO host synchronization
to the training step beyond the loss fetch the engine already performs. That
promise is easy to erode one innocent-looking ``device_get`` at a time, so
this test enforces it STATICALLY: it AST-scans utils/telemetry.py and
utils/numerics.py for the blocking primitives (``device_get``,
``block_until_ready``, ``np.asarray`` on device arrays) and pins the complete
allowlist of occurrences. A new fetch anywhere else is a test failure, not a
code review hope.
"""

import ast
import os

import deepspeed_tpu.utils.numerics as numerics_mod
import deepspeed_tpu.utils.pipeline_trace as pipeline_trace_mod
import deepspeed_tpu.utils.telemetry as telemetry_mod

FORBIDDEN_ATTRS = ("device_get", "block_until_ready")
FORBIDDEN_NUMPY = ("asarray",)


def _scan(module):
    """Return [(qualname, primitive)] for every forbidden call-ish reference."""
    src = open(module.__file__).read()
    tree = ast.parse(src, filename=module.__file__)
    hits = []

    class Scanner(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def _qual(self):
            return ".".join(self.stack) or "<module>"

        def visit_FunctionDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        def visit_Attribute(self, node):
            if node.attr in FORBIDDEN_ATTRS:
                hits.append((self._qual(), node.attr))
            elif node.attr in FORBIDDEN_NUMPY and isinstance(node.value, ast.Name) \
                    and node.value.id in ("np", "numpy"):
                hits.append((self._qual(), f"{node.value.id}.{node.attr}"))
            self.generic_visit(node)

    Scanner().visit(tree)
    return hits


def test_numerics_module_never_syncs():
    """utils/numerics.py is pure in-graph builders + host-side bookkeeping on
    ALREADY-FETCHED values: zero blocking primitives allowed."""
    assert _scan(numerics_mod) == []


def test_pipeline_trace_module_never_syncs():
    """utils/pipeline_trace.py records host timestamps at boundaries the
    executor already crosses: zero blocking primitives, zero exceptions."""
    assert _scan(pipeline_trace_mod) == []


def test_telemetry_module_sync_allowlist_is_exact():
    """utils/telemetry.py gets exactly two occurrences: the end_step loss-ride
    fetch (the one sanctioned block per step) and the np.asarray inside the
    abstract-signature helper (operates on shapes, not device buffers)."""
    hits = _scan(telemetry_mod)
    allowed = {
        ("TelemetrySession.end_step", "device_get"),
        ("_abstract_signature", "np.asarray"),
    }
    assert set(hits) <= allowed, f"new host-sync primitive introduced: {set(hits) - allowed}"
    # the sanctioned fetch must still exist (the scan itself stays honest)
    assert ("TelemetrySession.end_step", "device_get") in hits


def test_guard_scans_the_real_files():
    for mod in (numerics_mod, telemetry_mod, pipeline_trace_mod):
        assert os.path.exists(mod.__file__)
        assert mod.__file__.endswith(".py")
