"""Model-axis sharded paged decode: token identity, compile discipline,
constructor validation, and the unsharded path staying untouched.

The sharded engine (serve/paged.py sharded program set) splits attention
heads over the ``model`` mesh axis: each shard slices its own columns of
``c_attn_w`` / rows of ``c_proj_w``, attends over its local head shard of the
KV pool, and the layer output is one f32 ``psum`` per layer. That reduction
is mathematically the same sum the single-chip dot computes in a different
association order — so the contract is TOKEN identity against the unsharded
engine (greedy argmax and beam top-k are robust to sub-ulp drift under the
f32 accumulation), not bitwise HLO identity. The unsharded engine, by
contrast, must remain bit-identical to its pre-sharding self — mesh=None
returns the exact same program set, pinned here via the dense mirror oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.engine import InferenceEngine
from deepspeed_tpu.serve.scheduler import Request
from deepspeed_tpu.utils.telemetry import TelemetrySession

ML = 32


@pytest.fixture(scope="module")
def model_and_params():
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_and_params, **kw):
    model, params = model_and_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 33)
    kw.setdefault("max_model_len", ML)
    kw.setdefault("prefill_chunk", 8)
    return InferenceEngine(model, params, **kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 64, size=n).astype(np.int32).tolist()


def _mixed_trace():
    """Greedy + beam-4 + staggered arrivals + a preemption-prone mix."""
    reqs = [Request(f"r{i}", _prompt(i, 5 + i % 7), 6, arrival=i // 3,
                    num_beams=(4 if i == 3 else 1)) for i in range(6)]
    return reqs


def _clone(reqs):
    return [Request(r.req_id, list(r.prompt), r.max_new_tokens,
                    arrival=r.arrival, num_beams=r.num_beams) for r in reqs]


def test_sharded_token_identity_greedy_and_beam(model_and_params, eight_devices):
    base = _engine(model_and_params)
    outs1, logs1 = base.run(_clone(_mixed_trace()))
    shard = _engine(model_and_params, sharding={"model": 2})
    outs2, logs2 = shard.run(_clone(_mixed_trace()))
    assert [(o.req_id, o.status, o.tokens) for o in outs1] == \
           [(o.req_id, o.status, o.tokens) for o in outs2]
    # the beam request's final score survives the reduction-order change
    beam1 = [o for o in outs1 if o.req_id == "r3"][0]
    beam2 = [o for o in outs2 if o.req_id == "r3"][0]
    assert beam1.score == pytest.approx(beam2.score, rel=1e-5)
    # scheduling is sharding-blind: identical block tables + batch composition
    import json
    assert json.dumps(logs1) == json.dumps(logs2)


def test_sharded_zero_recompiles_after_warmup(model_and_params, eight_devices,
                                              tmp_path):
    """Per-iteration variation (tables, positions, lane masks) rides as array
    VALUES through the sharded programs too — each serve:* program compiles
    exactly once for the whole mixed trace."""
    session = TelemetrySession(output_path=str(tmp_path), job_name="shard")
    eng = _engine(model_and_params, sharding={"model": 2}, telemetry=session)
    eng.run(_clone(_mixed_trace()))
    names = [n for n in session.watchdog.records if n.startswith("serve:")]
    assert names, "no serve:* programs reached the compile watchdog"
    for n in names:
        assert session.watchdog.compiles(n) == 1, n
        assert session.watchdog.recompiles(n) == 0, n


def test_sharded_pallas_decode_token_identity(model_and_params, eight_devices):
    """The Pallas paged-decode kernel runs per-shard on the local head slice
    inside shard_map — same tokens as the pure-jnp sharded path."""
    a = _engine(model_and_params, sharding={"model": 2})
    outs_a, _ = a.run(_clone(_mixed_trace()))
    b = _engine(model_and_params, sharding={"model": 2}, use_pallas=True)
    outs_b, _ = b.run(_clone(_mixed_trace()))
    assert [o.tokens for o in outs_a] == [o.tokens for o in outs_b]


def test_unsharded_mirror_still_bitwise(model_and_params):
    """The mesh=None path must stay bit-identical to the dense oracle — the
    sharded lowering may not perturb a single unsharded HLO."""
    eng = _engine(model_and_params, mirror=True)
    outs, _ = eng.run([Request("m", _prompt(9, 9), 6)])
    assert outs[0].status == "finished"
    assert eng.mirror_checks > 0


def test_sharded_constructor_validation(model_and_params):
    with pytest.raises(ValueError, match="n_head"):
        _engine(model_and_params, sharding={"model": 3})   # 2 % 3 != 0
    with pytest.raises(ValueError, match="mirror"):
        _engine(model_and_params, sharding={"model": 2}, mirror=True)
    with pytest.raises(ValueError):
        _engine(model_and_params, sharding={"model": 0})
    # divisibility passes (16 % 16 == 0) so the device-count check fires;
    # validation raises before params are ever touched
    cfg16 = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=1,
                       n_head=16, compute_dtype=jnp.float32, loss_chunk=0)
    with pytest.raises(ValueError, match="devices"):
        InferenceEngine(GPT2Model(cfg16), None, num_slots=4, block_size=4,
                        num_blocks=9, max_model_len=ML, prefill_chunk=8,
                        sharding={"model": 16})


def test_sharded_pool_actually_sharded(model_and_params, eight_devices):
    """The KV pools really live sharded over the model axis (head dim split
    across 2 devices), not replicated — the memory win is the point."""
    eng = _engine(model_and_params, sharding={"model": 2})
    shards = eng.k_pool.addressable_shards
    assert len(shards) == 2
    n_head = eng.k_pool.shape[3]
    for s in shards:
        assert s.data.shape[3] == n_head // 2
