"""GPT-2 with block-sparse attention (GPT2Config.sparse_attention) — the Pallas
sparse kernel wired into the flagship causal LM, parity-tested against a dense
oracle that applies the same layout-expanded mask."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.ops.sparse_attention.sparsity_config import FixedSparsityConfig

V, T, E, NH, BLOCK = 97, 64, 32, 2, 16


def _sparse_cfg(**kw):
    return FixedSparsityConfig(num_heads=NH, block=BLOCK, num_local_blocks=2,
                               num_global_blocks=1, attention="unidirectional",
                               **kw)


class MaskedDenseGPT2(GPT2Model):
    """Oracle: attention core swapped for the maintained dense-masked reference
    (``dense_blocksparse_attention``) over the same layout, causal."""

    def __init__(self, config, layout):
        super().__init__(config)
        self._oracle_layout = np.asarray(layout)

    def _attention(self, x, p, dropout_rng=None):
        from deepspeed_tpu.ops.pallas.block_sparse_attention import \
            dense_blocksparse_attention
        c = self.config
        B, T_, _ = x.shape
        nh = c.n_head
        qkv = jnp.dot(x, p["c_attn_w"].astype(x.dtype),
                      preferred_element_type=jnp.float32).astype(x.dtype) \
            + p["c_attn_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T_, nh, c.head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, T_, nh, c.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, T_, nh, c.head_dim).transpose(0, 2, 1, 3)
        y = dense_blocksparse_attention(q, k, v, self._oracle_layout, BLOCK,
                                        causal=True)
        y = y.transpose(0, 2, 1, 3).reshape(B, T_, nh * c.head_dim)
        y = jnp.dot(y, p["c_proj_w"].astype(x.dtype),
                    preferred_element_type=jnp.float32)
        return y.astype(x.dtype) + p["c_proj_b"].astype(x.dtype)


def test_sparse_gpt2_matches_masked_dense_oracle():
    sc = _sparse_cfg()
    cfg = GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=2, n_head=NH,
                     compute_dtype=jnp.float32, sparse_attention=sc)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.default_rng(1).integers(0, V, (2, T)), jnp.int32)
    logits = np.asarray(model.logits(params, tokens))

    layout = sc.make_layout(T)
    oracle = MaskedDenseGPT2(
        GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=2, n_head=NH,
                   compute_dtype=jnp.float32), layout)
    want = np.asarray(oracle.logits(params, tokens))
    np.testing.assert_allclose(logits, want, rtol=2e-4, atol=2e-4)


def test_sparse_gpt2_trains():
    sc = _sparse_cfg()
    cfg = GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=2, n_head=NH,
                     compute_dtype=jnp.float32, sparse_attention=sc)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(2))
    tokens = jnp.asarray(np.random.default_rng(3).integers(0, V, (2, T)), jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)

    def loss_fn(p):
        return model.apply(p, tokens, labels)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert np.isfinite(float(loss))
    finite = all(bool(jnp.isfinite(g).all()) for g in jax.tree_util.tree_leaves(grads))
    assert finite
    # gradient flows into attention weights (the kernel's custom vjp is live)
    gw = grads["blocks"][0]["attn"]["c_attn_w"]
    assert float(jnp.abs(gw).max()) > 0


def test_sparse_gpt2_guards():
    sc = _sparse_cfg()
    with pytest.raises(AssertionError, match="dropout"):
        GPT2Model(GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=1,
                             n_head=NH, dropout=0.1, sparse_attention=sc))
    model = GPT2Model(GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=1,
                                 n_head=NH, sparse_attention=sc))
    with pytest.raises(AssertionError, match="manual TP"):
        model.with_tp("model", 2)
    with pytest.raises(AssertionError, match="ring"):
        model.with_sequence_parallel("data")
