"""GPT-2 training-loss semantics: ignore-label (-100) masking in both CE paths."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

V, T, E = 97, 64, 32


def _model(loss_chunk):
    cfg = GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32, loss_chunk=loss_chunk)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


@pytest.mark.parametrize("loss_chunk", [0, 16])  # unchunked + seq-chunked CE
def test_negative_labels_are_ignored(loss_chunk):
    model, params = _model(loss_chunk)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, V, (2, T)), jnp.int32)
    labels = np.roll(np.asarray(tokens), -1, axis=1)

    # oracle: per-position log-probs from the logits
    logp = np.asarray(jax.nn.log_softmax(
        jnp.asarray(np.asarray(model.logits(params, tokens), np.float32)), axis=-1))

    def oracle(lab):
        tot = n = 0.0
        for b in range(2):
            for t in range(T):
                if lab[b, t] >= 0:
                    tot -= logp[b, t, lab[b, t]]
                    n += 1
        return tot / max(n, 1)

    # mask the roll-wrapped last position (the documented use) + a random sprinkle
    lab = labels.copy()
    lab[:, -1] = -100
    lab[0, 5] = -100
    got = float(model.apply(params, tokens, jnp.asarray(lab)))
    np.testing.assert_allclose(got, oracle(lab), rtol=1e-5, atol=1e-5)

    # no ignored labels: same mean CE as before the masking feature
    got_full = float(model.apply(params, tokens, jnp.asarray(labels)))
    np.testing.assert_allclose(got_full, oracle(labels), rtol=1e-5, atol=1e-5)

    # all ignored: zero loss, no NaN from the 0/0 guard
    assert float(model.apply(params, tokens,
                             jnp.full((2, T), -100, jnp.int32))) == 0.0


def test_sequence_parallel_loss_weights_ignored_labels_globally():
    """Ranks hold UNEQUAL valid counts when -100 labels cluster in one chunk:
    the sp loss must be sum-loss/sum-count across ranks (a pmean of per-rank
    means would over-weight the masked rank), i.e. exactly the single-program
    masked loss."""
    from deepspeed_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(data=8, model=1, pipe=1)
    model, params = _model(0)
    sp_fn = model.sequence_parallel_loss_fn(mesh, "data")
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, V, (2, T)), jnp.int32)
    labels = np.roll(np.asarray(tokens), -1, axis=1)
    labels[:, T // 2:] = -100          # the back half (4 of 8 ranks) fully masked
    labels[:, 3] = -100                # plus a sprinkle in rank 0
    labels = jnp.asarray(labels)
    got = float(jax.jit(sp_fn)(params, tokens, labels))
    want = float(model.apply(params, tokens, labels))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
