"""Install/provenance surface (reference setup.py:19,320-324 discipline).

The package must expose version + git provenance + the native-op availability
map whether it was pip-installed (git_version_info_installed.py) or imported
from a source checkout (live fallback).
"""

import re
import subprocess
import sys

import deepspeed_tpu
from deepspeed_tpu import git_version_info


def test_version_shape():
    # "<semver>+<shorthash>" (or bare semver when git is unavailable at install)
    assert re.match(r"^\d+\.\d+\.\d+(\+[0-9a-f]{4,}|\+unknown)?$", deepspeed_tpu.__version__), \
        deepspeed_tpu.__version__
    assert deepspeed_tpu.__git_hash__ == git_version_info.git_hash


def test_installed_ops_map():
    ops = deepspeed_tpu.installed_ops
    assert set(ops) >= {"cpu_adam", "flash_attention", "block_sparse_attention",
                        "transformer"}
    assert all(isinstance(v, bool) for v in ops.values())
    # the kernels that compile with jax itself are always servable
    assert ops["flash_attention"] and ops["transformer"]


def test_pyproject_console_scripts_resolve():
    """Every console_script target must import and be callable (a broken entry
    point only surfaces at `pip install` otherwise)."""
    import importlib
    try:
        import tomllib
    except ImportError:  # py<3.11
        return
    with open(f"{_repo_root()}/pyproject.toml", "rb") as fd:
        meta = tomllib.load(fd)
    for target in meta["project"]["scripts"].values():
        mod, fn = target.split(":")
        assert callable(getattr(importlib.import_module(mod), fn)), target


def _repo_root():
    import os
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
