"""Cluster observatory tests (docs/cluster.md).

Fast single-process coverage of utils/cluster.py: heartbeat skew math and
straggler naming, clock-offset estimation under injected skew, the hang
watchdog (deadline fire, peer-signal fire, once-per-epoch), the exact
histogram-sketch merge behind the fleet serving rollups, the merged
post-mortem/timeline CLIs, and the core guarantee shared with every prior
observatory: the compiled step program is HLO-instruction-identical with
``telemetry.cluster`` enabled. The real 2-process aggregation path is
exercised by the slow rehearsal in test_launcher.py.
"""

import json
import os
import random
import threading
import time

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.serve.request_trace import HistogramSketch
from deepspeed_tpu.utils import cluster
from deepspeed_tpu.utils.cluster import (
    COL_DISPATCH_MS, COL_STEP_MS, HEARTBEAT_FIELDS, ClusterMonitor,
    HangWatchdog, ScopeTracker, assemble_cluster_report, cluster_dump_main,
    derive_cluster_stats, estimate_clock_offsets, find_straggler_host,
    fleet_latency_sketches, fleet_latency_summary, fleet_serving_totals,
    hang_sim_main, named_scope)
from deepspeed_tpu.utils.hlo import (collective_counts, instruction_count,
                                     optimized_hlo)
from deepspeed_tpu.utils.numerics import (FlightRecorder, load_run_bundles,
                                          merge_first_bad, scan_dump_dir_runs)
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def _row(step, wall, step_ms, dispatch_ms=None, ici=0.0, dcn=0.0, hbm=0.0):
    return [float(step), float(wall), float(step_ms),
            float(step_ms if dispatch_ms is None else dispatch_ms),
            float(ici), float(dcn), float(hbm)]


# ------------------------------------------------------------------ skew math
def test_straggler_rule_names_worst_host():
    # 4x the median -> named; the lower-middle median keeps the baseline an
    # actually-fast host
    s = find_straggler_host([10.0, 11.0, 40.0, 10.5], threshold=3.0)
    assert s["host"] == 2 and s["ratio"] == pytest.approx(40.0 / 10.5)
    # under the threshold -> nobody named
    assert find_straggler_host([10.0, 11.0, 12.0], threshold=3.0) is None
    # a single host can never be a straggler relative to itself
    assert find_straggler_host([10.0], threshold=3.0) is None
    # degenerate zero median -> no division, no naming
    assert find_straggler_host([0.0, 0.0], threshold=3.0) is None


def test_straggler_rule_two_host_world():
    """The regression the LOWER-middle median exists for: with 2 hosts the
    upper-middle median would BE the straggler, capping the ratio at 1."""
    s = find_straggler_host([10.0, 40.0], threshold=3.0)
    assert s == {"host": 1, "ratio": 4.0}


def test_derive_cluster_stats_skew_vs_attribution():
    """Skew scalars come from the step wall; the straggler is attributed from
    the host-local dispatch wall (collectives equalise the step wall)."""
    matrix = [_row(5, 1000.0, 200.0, dispatch_ms=10.0),
              _row(5, 1000.1, 201.0, dispatch_ms=160.0, ici=3.0, dcn=7.0)]
    stats = derive_cluster_stats(matrix, threshold=3.0)
    assert stats["step"] == 5 and stats["hosts"] == 2
    assert stats["step_ms_max"] == 201.0
    assert stats["step_skew"] == pytest.approx(201.0 / 200.0)
    assert stats["dispatch_ms_max"] == 160.0
    assert stats["wire_bytes_ici_total"] == 3.0
    assert stats["wire_bytes_dcn_total"] == 7.0
    # the near-equal step walls name nobody; the dispatch walls name host 1
    assert stats["straggler"] == {"host": 1, "ratio": pytest.approx(16.0)}
    assert list(HEARTBEAT_FIELDS).index("step_ms") == COL_STEP_MS
    assert list(HEARTBEAT_FIELDS).index("dispatch_ms") == COL_DISPATCH_MS


def test_clock_offset_estimation_under_injected_skew():
    # host 1 runs 2.5 ms behind host 0, host 2 runs 4 ms ahead; one outlier
    # heartbeat (a delayed snapshot) must not move the median
    hb = []
    for s in range(7):
        w0 = 1000.0 + s
        jitter = 0.5 if s == 3 else 0.0  # host 1's snapshot delayed once
        hb.append([[s, w0, 1, 1, 0, 0, 0],
                   [s, w0 - 0.0025 + jitter, 1, 1, 0, 0, 0],
                   [s, w0 + 0.004, 1, 1, 0, 0, 0]])
    off = estimate_clock_offsets(hb)
    assert off[0] == 0.0
    assert off[1] == pytest.approx(-0.0025)
    assert off[2] == pytest.approx(0.004)
    assert estimate_clock_offsets([]) == []


# ------------------------------------------------------------- sketch algebra
def test_histogram_sketch_merge_is_exact():
    """N shards merged == one stream: same buckets, same counts, bitwise-same
    percentiles — the property the fleet rollup rests on."""
    rng = random.Random(7)
    vals = [rng.uniform(0.2, 800.0) for _ in range(2000)]
    single = HistogramSketch()
    shards = [HistogramSketch() for _ in range(5)]
    for i, v in enumerate(vals):
        single.add(v)
        shards[i % 5].add(v)
    merged = HistogramSketch.merged(
        HistogramSketch.from_dict(s.to_dict()) for s in shards)
    assert merged.count == single.count
    # buckets and counts are bitwise-identical; only the running float `total`
    # differs (summation order), and percentiles never read it
    md, sd = merged.to_dict(), single.to_dict()
    assert md.pop("total") == pytest.approx(sd.pop("total"))
    assert md == sd
    for p in (50, 90, 95, 99):
        assert merged.percentile(p) == single.percentile(p)


def test_histogram_sketch_geometry_mismatch_refused():
    a, b = HistogramSketch(), HistogramSketch(growth=1.1)
    b.add(1.0)
    with pytest.raises(ValueError, match="geometry"):
        a.merge_from(b)


def test_fleet_latency_summary_matches_single_stream():
    """Round-robin a request stream over 4 virtual replicas; the fleet summary
    from their merged sketches must equal the single-stream summary exactly."""
    rng = random.Random(3)
    metrics = ("ttft_ms", "e2e_ms")
    single = {m: HistogramSketch() for m in metrics}
    replicas = [{m: HistogramSketch() for m in metrics} for _ in range(4)]
    for i in range(600):
        for m in metrics:
            v = rng.uniform(1.0, 400.0)
            single[m].add(v)
            replicas[i % 4][m].add(v)
    bundles = [{"latency_sketches": {m: r[m].to_dict() for m in metrics}}
               for r in replicas]
    fleet = fleet_latency_summary(bundles, ps=(50, 95, 99))
    want = {f"{m}_p{p:g}": single[m].percentile(p)
            for m in metrics for p in (50, 95, 99)}
    assert fleet == want


def test_fleet_summary_empty_replica_folds_as_omission():
    """A replica that finished nothing (empty sketches, or the key absent
    entirely, or a None bundle) must fold bitwise-identically to leaving it
    out — an idle fleet slot cannot move the percentiles."""
    rng = random.Random(11)
    busy = HistogramSketch()
    for _ in range(300):
        busy.add(rng.uniform(0.5, 900.0))
    full = {"latency_sketches": {"ttft_ms": busy.to_dict()}}
    empties = [
        {"latency_sketches": {}},
        {"latency_sketches": {"ttft_ms": HistogramSketch().to_dict()}},
        {},
        None,
    ]
    want = fleet_latency_summary([full], ps=(50, 95, 99))
    for empty in empties:
        assert fleet_latency_summary([full, empty], ps=(50, 95, 99)) == want
        assert fleet_latency_summary([empty, full], ps=(50, 95, 99)) == want
    # the empty-sketch fold is exact at the bucket level too, not just at
    # the percentile read-out
    merged = fleet_latency_sketches(
        [full, {"latency_sketches": {"ttft_ms":
                                     HistogramSketch().to_dict()}}])
    md, bd = merged["ttft_ms"].to_dict(), busy.to_dict()
    assert md.pop("total") == pytest.approx(bd.pop("total"))
    assert md == bd


def test_fleet_merge_refuses_mismatched_sketch_geometry():
    """Two replicas tracing with different histogram geometry cannot merge
    exactly — the fold must refuse loudly, never silently rebucket."""
    a, b = HistogramSketch(), HistogramSketch(growth=1.1)
    a.add(5.0)
    b.add(5.0)
    bundles = [{"latency_sketches": {"ttft_ms": a.to_dict()}},
               {"latency_sketches": {"ttft_ms": b.to_dict()}}]
    with pytest.raises(ValueError, match="geometry mismatch"):
        fleet_latency_sketches(bundles)
    with pytest.raises(ValueError, match="geometry mismatch"):
        fleet_latency_summary(bundles)


def test_fleet_serving_totals_sums_spec_counters():
    """The fleet rollup must carry the speculation economics (and lifecycle
    counts) across the fold instead of silently dropping them."""
    bundles = [
        {"totals": {"drafted_tokens": 10, "accepted_draft_tokens": 7,
                    "wasted_draft_tokens": 3, "prefill_tokens": 100},
         "counts": {"finished": 4, "refused": 1, "shed": 0}},
        {"totals": {"drafted_tokens": 5, "accepted_draft_tokens": 5,
                    "wasted_draft_tokens": 0, "decode_tokens": 40},
         "counts": {"finished": 2, "shed": 3}},
        {},          # an idle replica contributes nothing
        None,        # and a dead one even less
    ]
    out = fleet_serving_totals(bundles)
    assert out["totals"] == {"drafted_tokens": 15,
                             "accepted_draft_tokens": 12,
                             "wasted_draft_tokens": 3,
                             "prefill_tokens": 100, "decode_tokens": 40}
    assert out["counts"] == {"finished": 6, "refused": 1, "shed": 3}
    assert fleet_serving_totals([]) == {"totals": {}, "counts": {}}


# ------------------------------------------------------------- scope tracking
def test_scope_tracker_and_named_scope():
    tr = ScopeTracker()
    assert tr.last_scope() is None
    with named_scope("ds_grad_bucket3", tracker=tr):
        pass
    scope = tr.last_scope()
    assert scope["name"] == "ds_grad_bucket3" and scope["age_s"] >= 0.0

    # inside jit, the entry records at TRACE time — and compiles fine
    tr2 = ScopeTracker()

    def f(x):
        with named_scope("ds_fwd_bwd", tracker=tr2):
            return x * 2.0
    np.testing.assert_allclose(jax.jit(f)(np.float32(3.0)), 6.0)
    assert tr2.last_scope()["name"] == "ds_fwd_bwd"


# ------------------------------------------------------------ hang watchdog
def test_watchdog_deadline_fire_dumps_and_marks(tmp_path):
    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path), host_id=0,
                         run_id="wdtest")
    tr = ScopeTracker()
    tr.enter("ds_grad_bucket1")
    wd = HangWatchdog(recorder=rec, deadline_s=0.05, dump_dir=str(tmp_path),
                      host_id=0, run_id="wdtest", tracker=tr, poll_s=0.01)
    try:
        wd.arm(4)
        deadline = time.monotonic() + 5.0
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        wd.stop()
    assert len(wd.fired) == 1
    fire = wd.fired[0]
    assert fire["origin"] == "deadline" and fire["step"] == 4
    assert fire["last_scope"] == "ds_grad_bucket1"
    assert any("ds-hang-watchdog" in k for k in fire["threads"])
    # the dump landed, run-namespaced, with the hang event inside
    assert rec.dump_count == 1
    bundle = json.load(open(rec.last_dump_path))
    assert bundle["run"] == "wdtest"
    assert any(e["event"] == "hang" for e in bundle["events"])
    # and the peer marker is in place for the other hosts
    assert os.path.exists(tmp_path / "cluster_hang_wdtest_e4_host0.json")


def test_watchdog_peer_signal_fires_without_ping_pong(tmp_path):
    """Host 1's watchdog sees host 0's marker, dumps with origin peer_signal,
    and writes NO marker of its own; re-scanning never re-fires the epoch."""
    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path), host_id=1,
                         run_id="wdtest")
    wd = HangWatchdog(recorder=rec, deadline_s=3600.0, dump_dir=str(tmp_path),
                      host_id=1, run_id="wdtest", poll_s=0.01)
    marker = tmp_path / "cluster_hang_wdtest_e2_host0.json"
    marker.write_text(json.dumps(
        {"epoch": 2, "step": 2, "host": 0, "last_scope": "ds_fwd_bwd"}))
    try:
        wd.arm(2)  # arming starts the thread; the long deadline never expires
        deadline = time.monotonic() + 5.0
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)  # a few more scan cycles: must not double-fire
    finally:
        wd.stop()
    assert len(wd.fired) == 1
    fire = wd.fired[0]
    assert fire["origin"] == "peer_signal" and fire["peer"] == 0
    assert fire["peer_scope"] == "ds_fwd_bwd"
    assert rec.dump_count == 1
    # no host-1 marker: a peer-signalled fire must not signal back
    assert not os.path.exists(tmp_path / "cluster_hang_wdtest_e2_host1.json")
    # a marker for a DIFFERENT run is ignored entirely
    assert wd.run_id == "wdtest"


def test_watchdog_disarm_prevents_fire(tmp_path):
    wd = HangWatchdog(recorder=None, deadline_s=0.03, dump_dir=str(tmp_path),
                      host_id=0, run_id="wdtest2", poll_s=0.01)
    try:
        wd.arm(1)
        wd.disarm()
        time.sleep(0.15)
    finally:
        wd.stop()
    assert wd.fired == []


# ----------------------------------------------------------- cluster monitor
class _FakeMonitor:
    def __init__(self):
        self.scalars = []
        self.events = []

    def add_scalar(self, name, value, step):
        self.scalars.append((name, value, step))

    def event(self, name, payload, step=None):
        self.events.append((name, payload, step))


def test_cluster_monitor_ingest_emits_and_records():
    mon = _FakeMonitor()
    cm = ClusterMonitor(monitor=mon, host_id=0, n_hosts=2, warmup_steps=1,
                        allgather=lambda row: [row])
    # warmup step: stats recorded, straggler suppressed (compile jitter)
    cm.ingest([_row(0, 1000.0, 9.0, dispatch_ms=2.0),
               _row(0, 1000.0, 9.5, dispatch_ms=90.0)], 0)
    assert cm.last_stats["straggler"] is None and not cm.stragglers
    # post-warmup: host 1's dispatch wall names it
    cm.ingest([_row(1, 1001.0, 9.0, dispatch_ms=2.0, ici=10.0),
               _row(1, 1001.0, 9.5, dispatch_ms=90.0, ici=10.0)], 1)
    assert [s["host"] for s in cm.stragglers] == [1]
    names = {n for n, _, _ in mon.scalars}
    assert {"Cluster/hosts", "Cluster/step_ms_max", "Cluster/step_skew",
            "Cluster/wire_bytes_ici_total", "Cluster/straggler_host"} <= names
    host_scalar = [v for n, v, s in mon.scalars
                   if n == "Cluster/straggler_host"]
    assert host_scalar == [-1, 1]  # -1 while nobody is named
    assert [n for n, _, _ in mon.events] == ["cluster_straggler"]
    b = cm.bundle()
    assert b["kind"] == "cluster" and b["n_hosts"] == 2
    assert b["fields"] == list(HEARTBEAT_FIELDS) and len(b["heartbeats"]) == 2
    s = cm.summary()
    assert s["straggler_host"] == 1 and s["heartbeats"] == 2
    cm.stop()


def test_cluster_monitor_non_rank0_stays_silent():
    mon = _FakeMonitor()
    cm = ClusterMonitor(monitor=mon, host_id=1, n_hosts=2, warmup_steps=0,
                        allgather=lambda row: [row])
    cm.ingest([_row(0, 1000.0, 9.0), _row(0, 1000.0, 9.5)], 0)
    assert mon.scalars == []  # every host derives, only host 0 emits
    assert cm.last_stats is not None
    cm.stop()


# ------------------------------------------------- dump scanning / reporting
def _write_dump(dirpath, name, bundle):
    with open(os.path.join(dirpath, name), "w") as f:
        json.dump(bundle, f)


def test_scan_dump_dir_groups_runs_and_legacy(tmp_path):
    d = str(tmp_path)
    _write_dump(d, "numerics_dump_runA_host0_0.json", {"host": 0})
    _write_dump(d, "numerics_dump_runA_host1_0.json", {"host": 1})
    _write_dump(d, "numerics_dump_host0_0.json", {"host": 0})  # legacy
    _write_dump(d, "not_a_dump.json", {})
    runs = scan_dump_dir_runs(d)
    assert sorted(runs) == ["", "runA"]
    assert [(e["host"], e["index"]) for e in runs["runA"]] == [(0, 0), (1, 0)]

    run_key, by_host = load_run_bundles(d, run="runA")
    assert run_key == "runA" and sorted(by_host) == [0, 1]
    # torn dump: skipped, the intact earlier dump still loads
    _write_dump(d, "numerics_dump_runA_host1_1.json", {"host": 1})
    with open(os.path.join(d, "numerics_dump_runA_host1_1.json"), "w") as f:
        f.write('{"torn": tru')
    _, by_host = load_run_bundles(d, run="runA")
    assert by_host[1] == {"host": 1}


def test_merge_first_bad_picks_min_step_then_host():
    assert merge_first_bad({0: {"first_bad_step": 7},
                            1: {"first_bad_step": 5},
                            2: {"first_bad_step": 5}}) == (5, 1)
    assert merge_first_bad({0: {"first_bad_step": None}}) == (None, None)


def test_assemble_cluster_report_orders_stalls_by_corrected_time(tmp_path):
    """Host 1's clock runs behind; with offsets applied its earlier raw
    timestamp must still order AFTER host 0's genuinely-earlier stall."""
    # heartbeat history says host 1's wall reads 2 s behind host 0's
    heartbeats = [[_row(s, 1000.0 + s, 10.0), _row(s, 998.0 + s, 10.0)]
                  for s in range(4)]

    def bundle(host, t_fire):
        b = {
            "host": host,
            "events": [{"event": "hang", "step": 3, "time": t_fire,
                        "payload": {"origin": "deadline", "epoch": 3,
                                    "step": 3, "host": host,
                                    "last_scope": f"scope{host}"}}],
        }
        if host == 0:
            b["cluster"] = {"heartbeats": heartbeats}
        return b
    by_host = {0: bundle(0, 100.0), 1: bundle(1, 99.0)}
    report = assemble_cluster_report(by_host, "runX")
    # corrected: host0 at 100.0, host1 at 99.0 - (-2.0) = 101.0 -> host 0 first
    assert report["first_stall"]["host"] == 0
    assert report["first_stall"]["scope"] == "scope0"
    assert report["run"] == "runX" and report["n_dumps"] == 2


# ----------------------------------------------------------------- the CLIs
def _run_hang_sim(tmp_path, tag):
    out = str(tmp_path / f"transcript_{tag}.json")
    dumps = str(tmp_path / f"dumps_{tag}")
    rc = hang_sim_main(["--json", out, "--dump-dir", dumps,
                        "--deadline", "0.1"])
    assert rc == 0
    return out, dumps


@pytest.mark.slow
def test_hang_sim_deterministic_and_cli_roundtrip(tmp_path, capsys):
    """Two hang-sim runs produce byte-identical transcripts (the property the
    lint gate's golden compare rests on), and cluster-dump over the produced
    dumps names the stalled host and the collective scope it died in."""
    out1, dumps1 = _run_hang_sim(tmp_path, "a")
    out2, _ = _run_hang_sim(tmp_path, "b")
    assert open(out1, "rb").read() == open(out2, "rb").read()
    t = json.load(open(out1))
    assert t["ok"] and t["detected_within_deadline"]
    assert t["stalled_host"] == 1 and t["stall_step"] == 3
    assert [d["host"] for d in t["dumps"]] == [0, 1]
    assert t["report"]["first_stall"] == {
        "host": 1, "step": 3, "scope": "ds_grad_bucket1", "origin": "deadline"}
    capsys.readouterr()

    rc = cluster_dump_main([dumps1])
    text = capsys.readouterr().out
    assert rc == 0
    assert "first stall    : host 1 at step 3 in scope 'ds_grad_bucket1'" in text

    rc = cluster_dump_main([dumps1, "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0 and rep["first_stall"]["host"] == 1

    # merged two-host timeline: one track group per host, host 1 shifted by
    # the heartbeat-estimated clock offset
    from deepspeed_tpu.utils.pipeline_trace import timeline_main
    trace_out = str(tmp_path / "cluster.trace.json")
    rc = timeline_main(["--cluster", dumps1, "--run", "hangsim",
                       "-o", trace_out])
    capsys.readouterr()
    assert rc == 0
    trace = json.load(open(trace_out))
    pids = {ev["pid"] for ev in trace["traceEvents"] if "pid" in ev}
    assert pids == {0, 1}
    # host 1's simulated wall reads 1.5 ms early -> offset -1500 us
    assert trace["otherData"]["clock_offsets_us"] == {"0": 0, "1": -1500}


def test_cluster_dump_empty_dir_is_an_error(tmp_path, capsys):
    assert cluster_dump_main([str(tmp_path)]) == 2
    assert "no flight-recorder dumps" in capsys.readouterr().err


def test_inspect_dump_directory_mode(tmp_path, capsys):
    """inspect-dump pointed at a DIRECTORY merges one run's per-host dumps:
    first bad step/host + a one-liner per host."""
    from deepspeed_tpu.utils.numerics import inspect_dump_main
    d = str(tmp_path)
    _write_dump(d, "numerics_dump_runZ_host0_0.json",
                {"host": 0, "first_bad_step": None, "events": [], "steps": []})
    _write_dump(d, "numerics_dump_runZ_host1_0.json",
                {"host": 1, "first_bad_step": 6, "events": [], "steps": []})
    rc = inspect_dump_main([d, "--json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["run"] == "runZ"
    assert rep["first_bad_step"] == 6 and rep["first_bad_host"] == 1
    assert sorted(rep["hosts"]) == ["0", "1"]


# ----------------------------------------------------- engine integration
def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))


def test_engine_cluster_heartbeats_single_process(tmp_path):
    """telemetry.cluster on a single-process engine: heartbeats accumulate
    (the allgather shortcuts to the local row), Cluster/* scalars land in the
    monitor stream, and the dispatch wall is a real sub-interval of the step
    wall."""
    eng = _build(telemetry={
        "enabled": True, "output_path": str(tmp_path), "job_name": "cl",
        "cluster": {"enabled": True, "hang_deadline_s": 30.0,
                    "dump_dir": str(tmp_path / "dumps"), "warmup_steps": 1}})
    assert eng._cluster is not None
    xs, ys = _batch()
    for _ in range(3):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    cm = eng._cluster
    assert len(cm.heartbeats) == 3
    assert all(len(m) == 1 and len(m[0]) == len(HEARTBEAT_FIELDS)
               for m in cm.heartbeats)
    assert cm.summary()["straggler_host"] is None  # one host, no straggler
    assert cm.watchdog is not None and cm.watchdog.fired == []
    # dispatch wall <= step wall, both positive once steps flowed
    assert eng.telemetry.last_step_ms > 0
    assert 0 <= eng.telemetry.last_dispatch_ms <= eng.telemetry.last_step_ms
    cm.stop()
    eng.telemetry.close()
    scal = open(os.path.join(str(tmp_path), "cl", "scalars.jsonl")).read()
    assert "Cluster/hosts" in scal and "Cluster/step_skew" in scal


def test_cluster_enabled_is_hlo_identical(tmp_path):
    """The core observatory guarantee: enabling telemetry.cluster changes
    NOTHING in the compiled step program — identical instruction and
    collective counts (everything the plane does is host-side)."""
    eng_off = _build(telemetry={"enabled": True,
                                "output_path": str(tmp_path / "off")})
    eng_on = _build(telemetry={
        "enabled": True, "output_path": str(tmp_path / "on"),
        "cluster": {"enabled": True, "hang_deadline_s": 30.0,
                    "dump_dir": str(tmp_path / "dumps")}})
    xs, ys = _batch()
    hlos = []
    for eng in (eng_off, eng_on):
        hlos.append(optimized_hlo(eng._jit_loss_and_grad, eng.params,
                                  eng.scaler_state.cur_scale, xs, ys))
    assert instruction_count(hlos[0]) > 0
    assert instruction_count(hlos[0]) == instruction_count(hlos[1])
    assert collective_counts(hlos[0]) == collective_counts(hlos[1])
    if eng_on._cluster is not None:
        eng_on._cluster.stop()
