"""Step-time anatomy tests (docs/anatomy.md).

Three layers, mirroring the subsystem's own structure:

* **roofline.py** — the chip-spec table and floor arithmetic, pure math.
* **anatomy.analyze_program** — overlap windows, exposure, level split, and
  the named zero-overlap opportunities on hand-written HLO fixtures (the CPU
  backend emits only synchronous collectives, so the async forms are
  exercised on fixtures exactly like test_hlo_parsers.py).
* **Engine scale** — the anatomy rides the telemetry watchdog without
  changing a single HLO instruction; the flat-vs-hierarchical comparison
  shows strictly less exposed DCN for both two-level modes (golden-pinned,
  the byte-stable file scripts/lint.sh diffs); ZeRO grad collectives are
  flagged zero-overlap; and the roofline invariant holds against measured
  step time (floor <= measured, ceiling >= measured MFU).

Regenerate the golden with:
    ds-tpu anatomy --entry standard --entry comm_hierarchical \
        --entry comm_compressed --entry comm_overlap \
        --entry comm_overlap_compressed \
        --comm-compare-out tests/unit/golden/anatomy_comm_compare.json
"""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import anatomy
from deepspeed_tpu.utils.hlo import instruction_count, optimized_hlo
from deepspeed_tpu.utils.roofline import (CHIP_SPECS, ChipSpec, resolve_spec,
                                          roofline)
from simple_model import SimpleModel, random_dataset, simple_config

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "anatomy_comm_compare.json")

SLICE_SETS = [frozenset(range(0, 4)), frozenset(range(4, 8))]
SPEC = resolve_spec("cpu-test")


# ----------------------------------------------------------------- roofline
def test_resolve_spec_table_and_overrides():
    spec = resolve_spec("tpu-v5e")
    assert spec.peak_tflops == CHIP_SPECS["tpu-v5e"].peak_tflops
    over = resolve_spec("tpu-v5e", hbm_gbps=1000.0)
    assert over.hbm_gbps == 1000.0
    assert over.peak_tflops == spec.peak_tflops  # 0 keeps the table value
    with pytest.raises(ValueError, match="unknown chip"):
        resolve_spec("tpu-v9000")


def test_roofline_floor_and_ceiling_arithmetic():
    spec = ChipSpec("t", peak_tflops=1.0, hbm_gbps=1.0, ici_gbps=1.0,
                    dcn_gbps=1.0)
    # 1e12 flops at 1 TFLOP/s = 1 s compute; 5e8 bytes at 1 GB/s = 0.5 s HBM
    rf = roofline(1e12, 5e8, exposed_ici_s=0.25, exposed_dcn_s=0.25, spec=spec)
    assert rf["compute_floor_s"] == pytest.approx(1.0)
    assert rf["hbm_floor_s"] == pytest.approx(0.5)
    # floor = binding bound (compute) + exposed comm
    assert rf["predicted_floor_s"] == pytest.approx(1.5)
    assert rf["mfu_ceiling"] == pytest.approx(1.0 / 1.5)
    # attribution against a measured time
    rf = roofline(1e12, 5e8, 0.25, 0.25, spec, measured_seconds=2.0)
    assert rf["hbm_bound_s"] == pytest.approx(0.0)   # compute binds, not HBM
    assert rf["host_gap_s"] == pytest.approx(0.5)


def test_roofline_hbm_bound_program():
    spec = ChipSpec("t", peak_tflops=1.0, hbm_gbps=1.0, ici_gbps=1.0,
                    dcn_gbps=1.0)
    rf = roofline(1e10, 2e9, 0.0, 0.0, spec, measured_seconds=3.0)
    assert rf["hbm_floor_s"] == pytest.approx(2.0)
    assert rf["compute_s"] == pytest.approx(0.01)
    assert rf["hbm_bound_s"] == pytest.approx(2.0 - 0.01)
    assert rf["host_gap_s"] == pytest.approx(1.0)


# ---------------------------------------------------------- analyze_program
# async all-reduce with a fat annotated dot inside the window: the window
# hides part (not all) of the wire time
PARTIAL_OVERLAP = """
HloModule m

ENTRY main {
  p0 = f32[262144]{0} parameter(0)
  a = f32[64,64]{1,0} parameter(1)
  b = f32[64,64]{1,0} parameter(2)
  ars = f32[262144]{0} all-reduce-start(p0), replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=add
  d = f32[64,64]{1,0} dot(f32[64,64]{1,0} a, f32[64,64]{1,0} b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ard = f32[262144]{0} all-reduce-done(f32[262144]{0} ars)
  ROOT out = f32[64,64]{1,0} add(d, d)
}
"""

# same collective, nothing scheduled in the window: async but zero overlap
EMPTY_WINDOW = """
HloModule m

ENTRY main {
  p0 = f32[262144]{0} parameter(0)
  ars = f32[262144]{0} all-reduce-start(p0), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=add
  ROOT ard = f32[262144]{0} all-reduce-done(f32[262144]{0} ars)
}
"""

SYNC_ONLY = """
HloModule m

ENTRY main {
  p0 = f32[1024]{0} parameter(0)
  ar = f32[1024]{0} all-reduce(p0), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=add
  ROOT out = f32[1024]{0} add(ar, ar)
}
"""


def test_async_window_partially_hides_the_wire():
    r = anatomy.analyze_program(PARTIAL_OVERLAP, 1e6, 1e5, SPEC,
                                slice_sets=SLICE_SETS, name="p")
    (row,) = r["collectives"]
    assert row["async"] and not row["zero_overlap"]
    assert row["level"] == "ici"  # both groups stay inside one slice
    assert 0 < row["overlap_s"] < row["comm_s"]
    assert row["exposed_s"] == pytest.approx(row["comm_s"] - row["overlap_s"])
    assert r["exposed_s"]["ici"] == pytest.approx(row["exposed_s"])
    assert r["exposed_s"]["dcn"] == 0.0


def test_empty_async_window_is_zero_overlap_and_cross_slice():
    r = anatomy.analyze_program(EMPTY_WINDOW, 0, 0, SPEC,
                                slice_sets=SLICE_SETS, name="e")
    (row,) = r["collectives"]
    assert row["async"] and row["zero_overlap"]
    assert row["level"] == "dcn"  # the one group spans both slices
    assert row["overlap_s"] == 0.0
    assert row["exposed_s"] == pytest.approx(row["comm_s"])


def test_sync_collective_is_fully_exposed():
    r = anatomy.analyze_program(SYNC_ONLY, 0, 0, SPEC,
                                slice_sets=SLICE_SETS, name="s")
    (row,) = r["collectives"]
    assert not row["async"] and row["zero_overlap"]
    assert row["exposed_s"] == pytest.approx(row["comm_s"]) and row["comm_s"] > 0


def test_no_slice_factorization_means_no_dcn():
    r = anatomy.analyze_program(SYNC_ONLY, 0, 0, SPEC, slice_sets=None,
                                name="s")
    assert r["exposed_s"]["dcn"] == 0.0
    assert r["exposed_s"]["ici"] > 0.0


# two-bucket grad exchange in the scheduled (synchronous) form the CPU
# backend emits: each bucket's producer -> reduce-scatter (ici) -> all-reduce
# (dcn) -> all-gather (ici) chain carries the ds_grad_bucket{k} scope, with a
# compute instruction inside each bucket's issue window and an untagged loss
# all-reduce that must keep the fully-exposed sync pricing
BUCKETED_SYNC = """
HloModule m

ENTRY main {
  p0 = f32[1024]{0} parameter(0)
  prod0 = f32[1024]{0} negate(p0), metadata={op_name="jit(f)/ds_grad_bucket0/pad"}
  rs0 = f32[256]{0} reduce-scatter(prod0), replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}, to_apply=add, metadata={op_name="jit(f)/ds_grad_bucket0/reduce_scatter"}
  c0 = f32[1024]{0} add(p0, p0)
  ar0 = f32[256]{0} all-reduce(rs0), replica_groups={{0,4},{1,5},{2,6},{3,7}}, to_apply=add, metadata={op_name="jit(f)/ds_grad_bucket0/psum"}
  ag0 = f32[1024]{0} all-gather(ar0), replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}, metadata={op_name="jit(f)/ds_grad_bucket0/all_gather"}
  prod1 = f32[1024]{0} negate(p0), metadata={op_name="jit(f)/ds_grad_bucket1/reshape"}
  rs1 = f32[256]{0} reduce-scatter(prod1), replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}, to_apply=add, metadata={op_name="jit(f)/ds_grad_bucket1/reduce_scatter"}
  c1 = f32[1024]{0} add(p0, p0)
  ar1 = f32[256]{0} all-reduce(rs1), replica_groups={{0,4},{1,5},{2,6},{3,7}}, to_apply=add, metadata={op_name="jit(f)/ds_grad_bucket1/psum"}
  ag1 = f32[1024]{0} all-gather(ar1), replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}, metadata={op_name="jit(f)/ds_grad_bucket1/all_gather"}
  loss = f32[] all-reduce(p0), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=add
  ROOT t = (f32[1024]{0}, f32[1024]{0}, f32[]) tuple(ag0, ag1, loss)
}
"""


def test_bucket_scope_regex_matches_comm_constant():
    """anatomy parses HLO text without importing jax, so it carries its own
    copy of the bucket scope — pin it to the comm subsystem's constant."""
    from deepspeed_tpu.comm.hierarchical import GRAD_BUCKET_SCOPE
    m = anatomy._BUCKET_RE.search(f"op_name=\"x/{GRAD_BUCKET_SCOPE}7/psum\"")
    assert m is not None and m.group(1) == "7"


def test_bucketed_sync_collectives_get_overlap_credit():
    """The eager-issue pricing of the bucketed exchange: every tagged ICI
    phase hides fully under the other bucket's in-flight DCN wire (equal
    buckets: all-gather wire time == the peer DCN psum wire time at the
    cpu-test 4x ICI:DCN ratio), the DCN phases hide only behind the compute
    in their own issue window (partial), and the untagged loss all-reduce
    keeps the fully-exposed synchronous pricing."""
    r = anatomy.analyze_program(BUCKETED_SYNC, 0, 0, SPEC,
                                slice_sets=SLICE_SETS, name="b")
    rows = r["collectives"]
    assert [row["bucket"] for row in rows] == [0, 0, 0, 1, 1, 1, None]
    for row in rows:
        if row["bucket"] is None:
            continue
        assert not row["async"] and not row["zero_overlap"]
        if row["level"] == "ici":
            assert row["exposed_s"] == pytest.approx(0.0)
            assert row["overlap_s"] == pytest.approx(row["comm_s"])
        else:
            # window compute (one 4 KB add) hides part of the DCN psum
            assert 0 < row["overlap_s"] < row["comm_s"]
            assert row["exposed_s"] == pytest.approx(
                row["comm_s"] - row["overlap_s"])
    loss = rows[-1]
    assert loss["bucket"] is None and loss["zero_overlap"]
    assert loss["exposed_s"] == pytest.approx(loss["comm_s"])
    assert r["exposed_s"]["ici"] == pytest.approx(0.0)
    # both DCN psums partially exposed — strictly between 0 and full wire
    dcn_wire = sum(row["comm_s"] for row in rows
                   if row["level"] == "dcn" and row["bucket"] is not None)
    assert 0 < r["exposed_s"]["dcn"] < dcn_wire + loss["comm_s"]


def test_opportunities_threshold_and_order():
    big = anatomy.analyze_program(EMPTY_WINDOW, 0, 0, SPEC, SLICE_SETS, "big")
    small = anatomy.analyze_program(SYNC_ONLY, 0, 0, SPEC, SLICE_SETS, "small")
    opps = anatomy.opportunities([small, big], min_bytes=1024)
    assert [o["program"] for o in opps] == ["big", "small"]  # bytes-descending
    assert "start" in opps[0]["hint"]          # async phrasing
    assert "synchronous" in opps[1]["hint"]    # sync phrasing
    # threshold drops the 4 KB sync all-reduce
    assert anatomy.opportunities([small], min_bytes=1 << 20) == []


def test_trace_events_lay_exposed_comm_after_the_floor():
    r = anatomy.analyze_program(PARTIAL_OVERLAP, 1e6, 1e5, SPEC,
                                SLICE_SETS, "p")
    trace = anatomy.to_anatomy_trace_events([r])
    slices = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    floor = [e for e in slices if e["cat"] == "roofline"]
    comm = [e for e in slices if e["cat"] == "exposed-comm"]
    assert len(floor) == 1 and len(comm) == 1
    assert floor[0]["tid"] == 0 and comm[0]["tid"] == 1
    # comm track starts where the binding floor ends (dur itself carries the
    # 1 us Perfetto visibility clamp, so compare against the floor args)
    bound_us = max(floor[0]["args"]["compute_floor_us"],
                   floor[0]["args"]["hbm_floor_us"])
    assert comm[0]["ts"] == pytest.approx(bound_us)
    assert trace["otherData"]["generator"] == "ds-tpu anatomy"


# ------------------------------------------------------------- engine scale
HIDDEN = 16


def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))


# the engine step-path matrix: the same four training paths the lint registry
# captures (standard two-jit, fused external-master single-jit, the unfused
# accumulation window, and ZeRO-Offload's host-tier split)
def _external_master_pair(n):
    from deepspeed_tpu.lint.registry import _external_master_pair as pair
    return pair(n)


STEP_PATHS = {
    "standard": dict(zero_optimization={"stage": 2}),
    "external_master_fused": dict(zero_optimization={"stage": 2},
                                  zero_allow_untested_optimizer=True),
    "external_master_accum": dict(train_batch_size=16,
                                  gradient_accumulation_steps=2,
                                  zero_optimization={"stage": 2},
                                  zero_allow_untested_optimizer=True),
    "zero_offload": dict(zero_optimization={"stage": 2, "cpu_offload": True}),
}


@pytest.mark.parametrize("path", sorted(STEP_PATHS))
def test_anatomy_keeps_every_step_path_hlo_identical(path, tmp_path):
    """THE non-perturbation gate: telemetry.anatomy prices artifacts the
    watchdog already holds — with it on, every program on all four engine
    step paths compiles to the instruction-identical HLO."""
    overrides = STEP_PATHS[path]
    kwargs = {}
    if "external_master" in path:
        kwargs["optimizer"] = _external_master_pair(4)
    model = SimpleModel(HIDDEN)
    engines = []
    for tel in (None, {"enabled": True, "output_path": str(tmp_path),
                       "anatomy": {"enabled": True}}):
        over = dict(overrides)
        if tel:
            over["telemetry"] = tel
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
            config_params=simple_config(**over), **kwargs)
        engines.append(eng)
    eng_off, eng_on = engines
    assert eng_on.telemetry.anatomy_spec is not None
    batch = _batch()
    progs_off = {n: (j, a) for n, j, a, _m in eng_off.lint_programs(batch)}
    progs_on = {n: (j, a) for n, j, a, _m in eng_on.lint_programs(batch)}
    assert sorted(progs_off) == sorted(progs_on)
    for name in sorted(progs_off):
        j_off, a_off = progs_off[name]
        j_on, a_on = progs_on[name]
        h_off = optimized_hlo(j_off, *a_off)
        h_on = optimized_hlo(j_on, *a_on)
        assert instruction_count(h_off) > 0, name
        assert instruction_count(h_off) == instruction_count(h_on), name


@pytest.fixture(scope="module")
def comm_entry_reports():
    """Anatomy reports for the flat/hierarchical/compressed/overlap registry
    entries, captured once per module (five engine builds)."""
    from deepspeed_tpu.lint import registry
    out = {}
    for entry in ("standard", "comm_hierarchical", "comm_compressed",
                  "comm_overlap", "comm_overlap_compressed"):
        artifacts = registry.capture_entry(entry)
        out[entry] = [anatomy.analyze_artifact(a, SPEC, slice_sets=SLICE_SETS)
                      for a in artifacts]
    return out


def test_hierarchical_and_compressed_expose_less_dcn(comm_entry_reports):
    """The headline claim of the two-level exchange, stated in anatomy terms:
    both hierarchical modes strictly reduce estimated exposed-DCN time."""
    def dcn(entry):
        return sum(r["exposed_s"]["dcn"] for r in comm_entry_reports[entry])
    flat = dcn("standard")
    assert flat > 0
    assert dcn("comm_hierarchical") < flat
    assert dcn("comm_compressed") < flat


def test_overlap_entry_grad_collectives_are_bucketed_and_hidden(
        comm_entry_reports):
    """The overlap acceptance shape on the real registry programs: the
    bucketed exchange's collectives carry their bucket ids, every ICI phase
    is fully hidden (exposed == 0), nothing bucketed is zero-overlap, and no
    grad collective survives into the opportunity list."""
    reports = {r["name"]: r for r in comm_entry_reports["comm_overlap"]}
    rows = reports["comm_overlap:loss_and_grad"]["collectives"]
    tagged = [r for r in rows if r["bucket"] is not None]
    assert {r["bucket"] for r in tagged} == {0, 1, 2}
    assert all(not r["zero_overlap"] for r in tagged)
    assert all(r["exposed_s"] == 0.0 for r in tagged if r["level"] == "ici")
    opps = anatomy.opportunities(comm_entry_reports["comm_overlap"])
    assert not [o for o in opps if "loss_and_grad" in o["program"]], opps


def test_zero_grad_collective_is_flagged_zero_overlap(comm_entry_reports):
    """>= 1 ZeRO gradient collective surfaces as a named opportunity: the CPU
    backend schedules collectives synchronously, so the grad exchange in
    loss_and_grad is fully exposed and crosses the opportunity threshold."""
    reports = comm_entry_reports["standard"]
    opps = anatomy.opportunities(reports)
    grad = [o for o in opps if "loss_and_grad" in o["program"]
            and o["op"] in ("all-reduce", "reduce-scatter")]
    assert grad, f"no zero-overlap grad collective in {opps}"
    assert all(o["exposed_us"] > 0 for o in grad)


def test_comm_compare_matches_golden_bytes(comm_entry_reports):
    """The flat-vs-hierarchical comparison, byte-for-byte against the pinned
    golden (the same file scripts/lint.sh regenerates and diffs in CI)."""
    compare = anatomy.comm_compare(comm_entry_reports)
    assert compare is not None and compare["ok"]
    text = json.dumps(compare, indent=2, sort_keys=True) + "\n"
    with open(GOLDEN) as f:
        golden = f.read()
    assert text == golden, "comm compare drifted from golden (regen via " \
                           "ds-tpu anatomy --comm-compare-out, see module doc)"


def test_roofline_sanity_against_measured_step(tmp_path):
    """floor <= measured and ceiling >= measured MFU: the cpu-test spec is an
    upper bound on any CI machine, so the prediction brackets reality."""
    eng = _build(zero_optimization={"stage": 2},
                 telemetry={"enabled": True, "output_path": str(tmp_path),
                            "anatomy": {"enabled": True}})
    xs, ys = _batch()
    for _ in range(4):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    summary = eng.telemetry.summary()
    rf = summary["anatomy"]
    assert rf is not None
    assert rf["predicted_floor_ms"] <= summary["step_time_ms"]
    assert rf["mfu_ceiling"] >= (summary["mfu"] or 0.0)
    assert rf["host_gap_ms"] >= 0.0
    # the Anatomy/* scalars landed in the ledger
    eng.telemetry.close()
    path = os.path.join(str(tmp_path), "DeepSpeedTelemetry", "scalars.jsonl")
    tags = {json.loads(l)["tag"] for l in open(path)}
    assert {"Anatomy/predicted_floor_ms", "Anatomy/mfu_ceiling",
            "Anatomy/host_gap_ms", "Anatomy/compute_ms",
            "Anatomy/hbm_bound_ms", "Anatomy/exposed_ici_ms",
            "Anatomy/exposed_dcn_ms"} <= tags


def test_anatomy_off_emits_no_anatomy_scalars(tmp_path):
    eng = _build(telemetry={"enabled": True, "output_path": str(tmp_path)})
    assert eng.telemetry.anatomy_spec is None
    xs, ys = _batch()
    for _ in range(2):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    assert eng.telemetry.summary()["anatomy"] is None
    eng.telemetry.close()
    path = os.path.join(str(tmp_path), "DeepSpeedTelemetry", "scalars.jsonl")
    tags = {json.loads(l)["tag"] for l in open(path)}
    assert not any(t.startswith("Anatomy/") for t in tags)
