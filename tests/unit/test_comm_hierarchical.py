"""Hierarchical comm subsystem tests (docs/multislice.md).

Covers the topology factorization, the two-level schedules' numerics contract
(bit-equality on integer-valued data where every partial sum is exact,
tolerance parity on real training — the reduction is reassociated, not
changed), the ISSUE-8 acceptance gates (>= 20-step loss parity, >= 8x
cross-slice byte reduction HLO-pinned via the per-level wire-byte ledger,
clean per-level desync audit on the 2x4-factorized mesh), and the replica-
group parser / ICI-DCN classifier the ledger is built on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm import CommTopology, derive_num_slices, derive_topology
from deepspeed_tpu.comm.hierarchical import (error_state_shapes,
                                             two_level_allreduce,
                                             two_level_compressed_allreduce)
from deepspeed_tpu.parallel.mesh import DATA_AXIS, build_mesh
from deepspeed_tpu.utils.hlo import (collective_axis_breakdown,
                                     collective_axis_bytes,
                                     collective_bytes, optimized_hlo,
                                     parse_replica_groups)
from deepspeed_tpu.utils.numerics import compare_audit_rows
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


# ------------------------------------------------------------------- topology
def test_derive_num_slices_rules():
    # explicit request wins and must divide dp
    assert derive_num_slices(8, 4) == 4
    with pytest.raises(ValueError, match="does not divide"):
        derive_num_slices(8, 3)
    # auto: one slice per process when the processes tile the axis
    assert derive_num_slices(8, 0, process_count=2) == 2
    assert derive_num_slices(6, 0, process_count=3) == 3
    assert derive_num_slices(6, 0, process_count=4) == 1  # 4 does not tile 6
    # auto single-process: the canonical 8-device test mesh is virtually 2x4
    assert derive_num_slices(8, 0, process_count=1) == 2
    assert derive_num_slices(4, 0, process_count=1) == 1
    assert derive_topology(8, 0, process_count=1) == CommTopology(8, 2)


def test_topology_groups_and_positions():
    t = CommTopology(8, 2)
    assert (t.dp, t.num_slices, t.slice_size) == (8, 2, 4)
    assert t.is_hierarchical
    assert t.ici_groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert t.dcn_groups == [[0, 4], [1, 5], [2, 6], [3, 7]]
    assert t.slice_rows == t.ici_groups
    assert [t.slice_of(r) for r in range(8)] == [0, 0, 0, 0, 1, 1, 1, 1]
    # every device appears exactly once per level
    assert sorted(sum(t.ici_groups, [])) == list(range(8))
    assert sorted(sum(t.dcn_groups, [])) == list(range(8))
    # degenerate single slice: flat
    flat = CommTopology(8, 1)
    assert not flat.is_hierarchical and flat.ici_groups == [list(range(8))]
    with pytest.raises(ValueError, match="not divisible"):
        CommTopology(8, 3)


def test_slice_device_sets_include_model_fiber(eight_devices):
    # pure-dp mesh: slices are contiguous device halves
    mesh = build_mesh(data=8)
    t = CommTopology(8, 2)
    sets = t.slice_device_sets(mesh)
    assert sets == [frozenset(range(4)), frozenset(range(4, 8))]
    # dp=4 x model=2: each data rank's whole model fiber joins its slice, so
    # model-axis collectives inside one data shard classify as ICI
    mesh2 = build_mesh(data=4, model=2)
    t2 = CommTopology(4, 2)
    sets2 = t2.slice_device_sets(mesh2)
    assert len(sets2) == 2 and sets2[0] | sets2[1] == set(range(8))
    assert sets2[0].isdisjoint(sets2[1])
    flat_dev = [d.id for d in np.asarray(mesh2.devices).reshape(4, 2)[:2].ravel()]
    assert sets2[0] == frozenset(flat_dev)


def test_error_state_shapes():
    assert error_state_shapes(1024, CommTopology(8, 2)) == ((8, 256), (8, 128))
    # flat slice_size == 1 keeps the historical (dp, n) worker layout
    assert error_state_shapes(1024, CommTopology(8, 8)) == ((8, 1024), (8, 128))


# ------------------------------------------------------------------ numerics
def test_two_level_mean_bit_equal_flat_on_integer_data(eight_devices):
    """On integer-valued data every partial sum is exact, so the reassociated
    two-level mean must be BIT-equal to the flat mean (the generic-fp32 case
    is tolerance-only by design — reassociation changes rounding)."""
    mesh = build_mesh(data=8)
    topo = CommTopology(8, 2)
    rng = np.random.default_rng(0)
    rows = rng.integers(-512, 512, size=(8, 4096)).astype(np.float32)
    x = jax.device_put(rows, NamedSharding(mesh, P(DATA_AXIS, None)))
    hier = np.asarray(jax.jit(
        lambda v: two_level_allreduce(mesh, v, topo))(x))
    flat = rows.mean(axis=0, dtype=np.float32)
    np.testing.assert_array_equal(hier, flat)


def test_compressed_allreduce_flat_topology_matches_historical(eight_devices):
    """slice_size == 1 (every device its own slice) must reproduce the flat
    compressed_allreduce's math and EF layout exactly — same inputs, same
    output, same residuals."""
    from deepspeed_tpu.runtime.custom_collectives import compressed_allreduce
    mesh = build_mesh(data=8)
    topo = CommTopology(8, 8)
    n = 1024
    rng = np.random.default_rng(1)
    rows = rng.normal(size=(8, n)).astype(np.float32)
    sh = NamedSharding(mesh, P(DATA_AXIS, None))
    x = jax.device_put(rows, sh)
    we = jax.device_put(np.zeros((8, n), np.float32), sh)
    se = jax.device_put(np.zeros((8, n // 8), np.float32), sh)
    out_h, we_h, se_h = two_level_compressed_allreduce(mesh, x, we, se, topo)
    out_f, we_f, se_f = compressed_allreduce(mesh, x, we, se)
    np.testing.assert_array_equal(np.asarray(out_h), np.asarray(out_f))
    np.testing.assert_array_equal(np.asarray(we_h), np.asarray(we_f))
    np.testing.assert_array_equal(np.asarray(se_h), np.asarray(se_f))


# ------------------------------------------------------- engine loss parity
def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _train(eng, steps, seed=0):
    data = random_dataset(8, HIDDEN, seed=seed)
    xs = np.stack([d[0] for d in data])
    ys = np.stack([d[1] for d in data])
    losses = []
    for _ in range(steps):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_hierarchical_loss_parity_20_steps():
    """ISSUE-8 acceptance: training loss parity flat vs hierarchical over
    >= 20 steps on the 2x4-factorized mesh (same mean, reassociated — the
    documented tolerance, not bits)."""
    flat = _train(_build(zero_optimization={"stage": 2}), 21)
    hier = _train(_build(zero_optimization={"stage": 2},
                         comm={"mode": "hierarchical"}), 21)
    np.testing.assert_allclose(hier, flat, rtol=2e-3, atol=2e-4)
    assert flat[-1] < flat[0]  # both actually trained
    assert hier[-1] < hier[0]


def test_compressed_warmup_bit_equal_then_documented_tolerance():
    """hierarchical_compressed: steps before comm.compress_start_step run the
    UNCOMPRESSED hierarchical program (bit-equal losses); compressed steps
    stay within the documented 1-bit tolerance and keep training. The
    engine-held EF residuals become nonzero exactly at the phase switch."""
    hier = _build(zero_optimization={"stage": 2}, comm={"mode": "hierarchical"})
    comp = _build(zero_optimization={"stage": 2},
                  comm={"mode": "hierarchical_compressed",
                        "compress_start_step": 3})
    assert np.asarray(comp._comm_we).any() == False  # noqa: E712 — zero-init
    l_hier = _train(hier, 21)
    l_comp = _train(comp, 21)
    np.testing.assert_array_equal(l_comp[:3], l_hier[:3])  # warmup: same program
    assert max(abs(a - b) for a, b in zip(l_comp[3:], l_hier[3:])) < 0.1
    assert l_comp[-1] < l_comp[0]
    assert np.asarray(comp._comm_we).any()  # EF residual accumulated
    assert np.asarray(comp._comm_se).any()


# -------------------------------------------------------- per-level desync
def test_compare_audit_rows_classifies_levels():
    names = ["w1", "w2"]
    rows = CommTopology(4, 2).slice_rows
    clean = [[7, 9]] * 4
    assert compare_audit_rows(clean, names, slice_rows=rows) is None
    # slices internally consistent but disagreeing -> the DCN hop is the culprit
    cross = [[7, 9], [7, 9], [8, 9], [8, 9]]
    div = compare_audit_rows(cross, names, slice_rows=rows)
    assert div["subtree"] == "w1" and div["level"] == "cross_slice"
    assert div["diverging_slices"] == [1]
    assert div["diverging_replicas"] == [2, 3]
    # a slice disagreeing with itself -> ICI exchange / local compute
    intra = [[7, 9], [6, 9], [7, 9], [7, 9]]
    div = compare_audit_rows(intra, names, slice_rows=rows)
    assert div["level"] == "intra_slice"
    # without a topology there is no level classification
    div = compare_audit_rows(cross, names)
    assert "level" not in div and div["diverging_replicas"] == [2, 3]


def test_desync_audit_clean_on_factorized_mesh():
    """ISSUE-8 acceptance: the per-level audit runs against the hierarchical
    engine's replicated state and flags nothing on a healthy run."""
    eng = _build(zero_optimization={"stage": 2},
                 comm={"mode": "hierarchical_compressed"},
                 numerics={"enabled": True, "audit_interval": 2})
    _train(eng, 4)
    assert eng._comm_topo.is_hierarchical
    assert eng._numerics.audit_runs == 2
    assert eng._numerics.desync is None


# ------------------------------------------------- HLO wire-byte acceptance
def test_dcn_byte_reduction_hlo_pinned():
    """ISSUE-8 acceptance: compiled hierarchical_compressed step shows >= 8x
    fewer cross-slice bytes than the flat fp32 exchange, measured on the
    per-axis wire-byte ledger over the engines' own grad programs. hidden=64
    (not the parity tests' 16): the toy-16 model sits entirely under ZeRO's
    min-size sharding floor and its step compiles with no collectives at all —
    there would be nothing to measure."""
    hidden = 64

    def build(**overrides):
        model = SimpleModel(hidden)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
            config_params=simple_config(**overrides))
        return eng

    flat_eng = build(zero_optimization={"stage": 2})
    comp_eng = build(zero_optimization={"stage": 2},
                     comm={"mode": "hierarchical_compressed"})
    topo = comp_eng._comm_topo
    slice_sets = topo.slice_device_sets(comp_eng.mesh)
    data = random_dataset(8, hidden, seed=0)
    xs = np.stack([d[0] for d in data])
    ys = np.stack([d[1] for d in data])

    flat_txt = optimized_hlo(flat_eng._jit_loss_and_grad, flat_eng.params,
                             flat_eng.scaler_state.cur_scale, xs, ys)
    comp_txt = optimized_hlo(comp_eng._jit_loss_and_grad_comm, comp_eng.params,
                             comp_eng.scaler_state.cur_scale,
                             comp_eng._comm_we, comp_eng._comm_se, xs, ys)
    flat_ax = collective_axis_bytes(flat_txt, slice_sets)
    comp_ax = collective_axis_bytes(comp_txt, slice_sets)
    assert flat_ax["dcn"] > 0
    assert comp_ax["dcn"] > 0
    reduction = flat_ax["dcn"] / comp_ax["dcn"]
    assert reduction >= 8.0, (
        f"cross-slice bytes reduced only {reduction:.1f}x "
        f"(flat {flat_ax}, compressed {comp_ax})")
    # the two buckets always sum exactly to the unclassified total
    assert flat_ax["ici"] + flat_ax["dcn"] == collective_bytes(flat_txt)
    assert comp_ax["ici"] + comp_ax["dcn"] == collective_bytes(comp_txt)


def test_axis_breakdown_sums_match_axis_bytes(eight_devices):
    mesh = build_mesh(data=8)
    topo = CommTopology(8, 2)
    x = jax.device_put(np.ones((8, 4096), np.float32),
                       NamedSharding(mesh, P(DATA_AXIS, None)))
    txt = optimized_hlo(jax.jit(lambda v: two_level_allreduce(mesh, v, topo)), x)
    sets = topo.slice_device_sets(mesh)
    ax = collective_axis_bytes(txt, sets)
    br = collective_axis_breakdown(txt, sets)
    for lvl in ("ici", "dcn"):
        assert sum(ops[lvl]["bytes"] for ops in br.values()) == ax[lvl]
    assert sum(ops["ici"]["count"] + ops["dcn"]["count"]
               for ops in br.values()) >= 2


# ----------------------------------------------------- replica-group parser
def test_parse_replica_groups_forms():
    # explicit groups
    assert parse_replica_groups(
        "x = f32[4] all-reduce(y), replica_groups={{0,1},{2,3}}") \
        == [(0, 1), (2, 3)]
    # iota form with transpose: [2,4]<=[4,2]T(1,0) -> columns become rows
    got = parse_replica_groups(
        "x = f32[4] all-gather(y), replica_groups=[2,4]<=[4,2]T(1,0)")
    assert got == [(0, 2, 4, 6), (1, 3, 5, 7)]
    # iota without transpose
    assert parse_replica_groups(
        "x = f32[4] all-gather(y), replica_groups=[2,2]<=[4]") \
        == [(0, 1), (2, 3)]
    # empty grouping and no grouping both mean "all devices, one group"
    assert parse_replica_groups(
        "x = f32[4] all-reduce(y), replica_groups={}") is None
    assert parse_replica_groups("x = f32[4] all-reduce(y)") is None
    # collective-permute names pairs instead
    assert parse_replica_groups(
        "x = f32[4] collective-permute(y), source_target_pairs={{0,1},{1,0}}") \
        == [(0, 1), (1, 0)]


# --------------------------------------------------------------- comm-sim
@pytest.mark.slow
def test_comm_sim_report_passes_manifest():
    """The comm-sim gate (scripts/lint.sh) holds on the shipped schedule and
    its JSON rendering is deterministic and parseable."""
    import json as _json
    from deepspeed_tpu.comm.sim import MIN_DCN_REDUCTION, build_report, render
    report = build_report(num_slices=2)
    assert report["ok"], report["violations"]
    assert report["dcn_reduction_vs_flat"] >= MIN_DCN_REDUCTION
    assert report["mesh"]["num_slices"] == 2
    text = render(report)
    assert text.endswith("\n") and _json.loads(text) == _json.loads(text)
    assert render(report) == text
