"""Resilience-layer tests (docs/resilience.md; PR 13 acceptance).

Covers the commit protocol (manifest checksums refuse torn checkpoints, the
``latest`` pointer is written atomically), async snapshot consistency (a save
issued mid-run restores the state AT the save point, not whatever the engine
mutated afterwards), topology-changing restore (ZeRO-2 dp=4 -> dp=2/dp=8
loss-trajectory parity, bucketed-overlap EF bit-equal continuation + elastic
remap + geometry refusal), flight-recorder-driven auto-resume selection, the
serving warm-restart state round-trip, and HLO-instruction-identity of the
step programs with the resilience block enabled (everything is host-side).
"""

import json
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.checkpoint.checkpointing import (MANIFEST_NAME,
                                                    verify_checkpoint,
                                                    write_latest)
from deepspeed_tpu.resilience import (AsyncCheckpointer, auto_resume,
                                      find_resume_point, restore_server,
                                      save_server)
from deepspeed_tpu.utils.hlo import optimized_hlo
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def make_engine(cfg, seed=0, hidden=HIDDEN):
    model = SimpleModel(hidden)
    params = model.init(jax.random.PRNGKey(seed))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg)
    return engine


def batches(n, hidden=HIDDEN, seed=0, batch=8):
    """Explicit global batches so engines of DIFFERENT dp sizes consume the
    identical sample stream (each shards the same (batch, hidden) arrays)."""
    rng = np.random.default_rng(seed)
    w = np.random.default_rng(99).normal(size=(hidden, hidden)).astype(
        np.float32) * 0.3
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, hidden)).astype(np.float32)
        out.append((x, np.tanh(x @ w)))
    return out


def train(engine, bs):
    losses = []
    for x, y in bs:
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def trees_equal(a, b, rtol=0.0, atol=0.0):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


# ------------------------------------------------------- commit protocol
def test_manifest_verifier_detects_torn_checkpoint(tmp_path):
    """Every committed file is checksummed; truncation, bit-rot, and missing
    files are all detected — and load_checkpoint REFUSES the tag."""
    engine = make_engine(simple_config())
    train(engine, batches(2))
    engine.save_checkpoint(str(tmp_path), tag="t")
    ckpt = tmp_path / "t"
    assert verify_checkpoint(str(ckpt)) == (True, "ok")

    shard = ckpt / "zero_pp_rank_0_mp_rank_00_optim_states.npz"
    orig = shard.read_bytes()
    shard.write_bytes(orig[: len(orig) // 2])  # torn write
    ok, reason = verify_checkpoint(str(ckpt))
    assert not ok and "size mismatch" in reason
    engine2 = make_engine(simple_config(), seed=5)
    path, cs = engine2.load_checkpoint(str(tmp_path), tag="t")
    assert path is None and cs == {}  # refused, never loaded

    flipped = bytearray(orig)
    flipped[len(orig) // 2] ^= 0xFF  # bit rot at the original size
    shard.write_bytes(bytes(flipped))
    ok, reason = verify_checkpoint(str(ckpt))
    assert not ok and "checksum mismatch" in reason

    shard.write_bytes(orig)
    assert verify_checkpoint(str(ckpt))[0]
    shard.unlink()
    ok, reason = verify_checkpoint(str(ckpt))
    assert not ok and "missing" in reason

    shard.write_bytes(orig)
    (ckpt / MANIFEST_NAME).unlink()  # pre-resilience checkpoints still load
    ok, reason = verify_checkpoint(str(ckpt))
    assert ok and "legacy" in reason


def test_latest_pointer_write_is_atomic(tmp_path):
    write_latest(str(tmp_path), "step1")
    assert (tmp_path / "latest").read_text() == "step1"
    write_latest(str(tmp_path), "step2")
    assert (tmp_path / "latest").read_text() == "step2"
    # the tmp file used for the atomic replace never survives
    assert [p.name for p in tmp_path.iterdir()] == ["latest"]


def test_tmp_carcass_is_invisible_to_restore(tmp_path):
    """A fully-written but never-renamed <tag>.tmp (death mid-commit) is
    skipped by tag enumeration and auto-resume."""
    engine = make_engine(simple_config())
    train(engine, batches(2))
    engine.save_checkpoint(str(tmp_path), tag="good")
    (tmp_path / "bad.tmp").mkdir()
    (tmp_path / "bad.tmp" / "junk.npz").write_bytes(b"x")
    info = find_resume_point(str(tmp_path))
    assert info is not None and info["tag"] == "good"


# ---------------------------------------------------- async checkpointing
def test_async_save_snapshot_consistency(tmp_path):
    """The snapshot is taken on the caller thread at save(); training three
    MORE steps while the commit thread writes must not leak into the file —
    restore lands bit-equal on the save-point state."""
    bs = batches(6)
    engine = make_engine(simple_config())
    train(engine, bs[:3])
    at_save = jax.device_get(engine.master_params)
    ck = AsyncCheckpointer(engine, str(tmp_path))
    ck.save(tag="step3")
    train(engine, bs[3:])  # overlaps the background commit
    ck.wait()
    assert ck.saves_committed == 1
    assert ck.last_stall_ms >= 0.0

    engine2 = make_engine(simple_config(), seed=7)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None and engine2.global_steps == 3
    trees_equal(at_save, engine2.master_params)


# ------------------------------------------------- topology-changing restore
@pytest.mark.parametrize("dp_new", [2, 8])
def test_zero2_elastic_loss_trajectory_parity(tmp_path, eight_devices, dp_new):
    """Save ZeRO-2 at dp=4, restore at dp=2 / dp=8: the remaining loss
    trajectory matches the uninterrupted dp=4 oracle at pinned rtol."""
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    bs = batches(6)

    def build(dp, seed):
        model = SimpleModel(HIDDEN)
        mesh = build_mesh(data=dp, model=1, pipe=1,
                          devices=eight_devices[:dp])
        return DeepSpeedEngine(
            model=model, model_parameters=model.init(jax.random.PRNGKey(seed)),
            config_params=simple_config(zero_optimization={"stage": 2}),
            mesh=mesh)

    oracle = build(4, seed=0)
    oracle_losses = train(oracle, bs)

    saver = build(4, seed=0)
    train(saver, bs[:3])
    saver.save_checkpoint(str(tmp_path))

    resumed = build(dp_new, seed=31)  # different init: restore must win
    path, _ = resumed.load_checkpoint(str(tmp_path))
    assert path is not None and resumed.dp_size == dp_new
    resumed_losses = train(resumed, bs[3:])
    np.testing.assert_allclose(resumed_losses, oracle_losses[3:],
                               rtol=1e-5, atol=1e-7)
    trees_equal(oracle.master_params, resumed.master_params,
                rtol=1e-5, atol=1e-7)


COMPRESSED = dict(zero_optimization={"stage": 2},
                  comm={"mode": "hierarchical_compressed", "dcn_slices": 2,
                        "compress_start_step": 2,
                        "overlap": {"mode": "bucketed", "bucket_mb": 0.01}})


def _compressed_engine(seed=0, hidden=64, **cfg_overrides):
    cfg = {k: v for k, v in COMPRESSED.items()}
    cfg.update(cfg_overrides)
    return make_engine(simple_config(**cfg), seed=seed, hidden=hidden)


def test_comm_ef_roundtrip_bit_equal_continuation(tmp_path):
    """Bucketed-overlap EF buffers ride the checkpoint: after the compression
    warmup, save -> restore into a fresh engine -> compressed training
    continues BIT-EQUAL to the uninterrupted run (ISSUE satellite: the EF
    residual is part of the optimizer state, losing it is a regression)."""
    bs = batches(9, hidden=64)
    engine = _compressed_engine()
    train(engine, bs[:6])  # past compress_start_step: EF nonzero
    assert np.asarray(engine._comm_we).any()
    engine.save_checkpoint(str(tmp_path))
    uninterrupted = train(engine, bs[6:])

    engine2 = _compressed_engine(seed=13)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    resumed = train(engine2, bs[6:])
    assert resumed == uninterrupted  # bit-equal float-for-float


def test_comm_ef_elastic_remap_dp8_to_dp4(tmp_path, eight_devices):
    """EF buffers saved at dp=8 restore into a dp=4 engine: server residual
    carries over by exact permutation and compressed training continues."""
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    bs = batches(8, hidden=64)
    engine = _compressed_engine()
    assert engine.dp_size == 8
    train(engine, bs[:6])
    se_saved = np.asarray(engine._comm_se)
    assert se_saved.any()
    engine.save_checkpoint(str(tmp_path))

    model = SimpleModel(64)
    mesh4 = build_mesh(data=4, model=1, pipe=1, devices=eight_devices[:4])
    engine4 = DeepSpeedEngine(
        model=model, model_parameters=model.init(jax.random.PRNGKey(21)),
        config_params=simple_config(**COMPRESSED), mesh=mesh4)
    assert engine4.dp_size == 4
    path, _ = engine4.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine4._comm_se.shape[0] == 4
    # the global server residual is preserved exactly: reconstruct it from
    # both layouts bucket by bucket (server remap is a pure permutation)
    from deepspeed_tpu.ops.onebit_adam import OneBitAdam
    se_new = np.asarray(engine4._comm_se)
    L_o = engine._comm_topo.slice_size
    L_n = engine4._comm_topo.slice_size
    o_off = n_off = 0
    for b_old, b_new in zip(engine._overlap_plan, engine4._overlap_plan):
        npad_o, npad_n = b_old["n_pad"], b_new["n_pad"]
        cs_o, cs_n = npad_o // 8, npad_n // 4
        g_o = np.zeros(npad_o, np.float32)
        for d, off in enumerate(OneBitAdam._server_offsets(8, L_o, npad_o)):
            g_o[off:off + cs_o] = se_saved[d, o_off:o_off + cs_o]
        g_n = np.zeros(npad_n, np.float32)
        for d, off in enumerate(OneBitAdam._server_offsets(4, L_n, npad_n)):
            g_n[off:off + cs_n] = se_new[d, n_off:n_off + cs_n]
        keep = min(npad_o, npad_n)
        np.testing.assert_array_equal(g_n[:keep], g_o[:keep])
        o_off += cs_o
        n_off += cs_n
    # and the resized engine keeps training under compression
    resumed = train(engine4, bs[6:])
    assert all(np.isfinite(resumed))


def test_comm_ef_geometry_refusal(tmp_path):
    """A saved EF layout that does not replay under the live bucket plan is
    refused with ValueError — never silently sliced into the wrong chunks."""
    bs = batches(7, hidden=64)
    engine = _compressed_engine()
    train(engine, bs[:6])
    engine.save_checkpoint(str(tmp_path))

    mono = _compressed_engine(
        seed=3, comm={"mode": "hierarchical_compressed", "dcn_slices": 2,
                      "compress_start_step": 2,
                      "overlap": {"mode": "bucketed", "bucket_mb": 64.0}})
    with pytest.raises(ValueError, match="refusing"):
        mono.load_checkpoint(str(tmp_path))


# ------------------------------------------------------------- auto-resume
def test_auto_resume_selection_and_scale_clamp(tmp_path):
    """Newest-before-first-bad-step selection, torn-tag skip, and the
    journaled loss-scale clamp (no overflow-spiral replay)."""
    save_dir = tmp_path / "ckpts"
    dump_dir = tmp_path / "dumps"
    dump_dir.mkdir()
    bs = batches(5)
    engine = make_engine(simple_config(fp16={"enabled": True,
                                             "initial_scale_power": 10}))
    train(engine, bs[:2])
    engine.save_checkpoint(str(save_dir), tag="step2")
    train(engine, bs[2:4])
    engine.save_checkpoint(str(save_dir), tag="step4")

    # no dump: plain warm restart takes the newest commit
    assert find_resume_point(str(save_dir))["tag"] == "step4"

    (dump_dir / "numerics_dump_host0_0.json").write_text(json.dumps(
        {"first_bad_step": 3,
         "loss_scale_trajectory": [[2, 1024.0], [3, 256.0]]}))
    info = find_resume_point(str(save_dir), str(dump_dir))
    assert info["tag"] == "step2" and info["journal_scale"] == 256.0

    engine2 = make_engine(simple_config(fp16={"enabled": True,
                                              "initial_scale_power": 10}),
                          seed=9)
    path, _, info = auto_resume(engine2, str(save_dir), str(dump_dir))
    assert path is not None and engine2.global_steps == 2
    # the checkpoint recorded 1024; the journal had backed off to 256
    assert float(engine2.scaler_state.cur_scale) == 256.0


def test_scan_dump_dir_ignores_torn_dump(tmp_path):
    from deepspeed_tpu.utils.numerics import scan_dump_dir
    assert scan_dump_dir(None) is None
    assert scan_dump_dir(str(tmp_path / "missing")) is None
    (tmp_path / "numerics_dump_host0_0.json").write_text('{"first_bad')
    assert scan_dump_dir(str(tmp_path)) is None  # torn dump never blocks resume
    (tmp_path / "numerics_dump_host0_1.json").write_text(
        '{"first_bad_step": 7}')
    assert scan_dump_dir(str(tmp_path))["first_bad_step"] == 7


# ------------------------------------------------------- serving warm restart
def _server(seed=0, num_blocks=65):
    from deepspeed_tpu.resilience.crash_sim import _make_server
    return _make_server(seed, num_blocks)


def test_serve_state_roundtrip_token_identical(tmp_path):
    """Kill a serving replica mid-schedule, snapshot, restore into a fresh
    engine: the drained outputs are token-identical to the uninterrupted
    oracle and the ledger (allocator order, prefix index) round-trips."""
    from deepspeed_tpu.resilience.crash_sim import _drain, _serve_trace
    from deepspeed_tpu.serve.scheduler import pack_request, unpack_request

    trace = _serve_trace(0)
    oracle = _server(0)
    out, _ = oracle.run([unpack_request(pack_request(r)) for r in trace])
    want = {o.req_id: list(o.tokens) for o in out if o.status == "finished"}

    victim = _server(0)
    for r in trace:
        victim.submit(unpack_request(pack_request(r)))
    for _ in range(4):
        victim.step()
    snap_dir = save_server(victim, str(tmp_path))
    assert verify_checkpoint(snap_dir)[0]

    warm = _server(0)
    assert restore_server(warm, snap_dir)
    # allocator ledger round-trips ORDER-exactly (allocation determinism)
    assert (list(warm.scheduler.allocator._free)
            == list(victim.scheduler.allocator._free))
    assert (list(warm.scheduler.allocator._cached)
            == list(victim.scheduler.allocator._cached))
    _drain(warm)
    got = {rid: list(o.tokens) for rid, o in warm.outputs.items()
           if o.status == "finished"}
    assert got == want


def test_serve_restart_geometry_refusal(tmp_path):
    victim = _server(0)
    for _ in range(2):
        victim.step()
    snap_dir = save_server(victim, str(tmp_path))
    other = _server(0, num_blocks=33)  # different pool: indices meaningless
    with pytest.raises(ValueError, match="geometry"):
        restore_server(other, snap_dir)


def test_serve_torn_snapshot_refused(tmp_path):
    victim = _server(0)
    snap_dir = save_server(victim, str(tmp_path))
    pool = os.path.join(snap_dir, "serve_pool.npz")
    data = open(pool, "rb").read()
    with open(pool, "wb") as f:
        f.write(data[: len(data) // 2])
    fresh = _server(0)
    assert restore_server(fresh, snap_dir) is False  # cold start, not a crash


def test_prefix_chain_key_roundtrip():
    from deepspeed_tpu.serve.prefix_cache import chain_to_key, key_to_chain
    key = (((None, (1, 2, 3)), (4, 5, 6)), (7, 8))
    chain = key_to_chain(key)
    assert chain == [[1, 2, 3], [4, 5, 6], [7, 8]]
    back = chain_to_key(chain)
    assert back == key and hash(back) == hash(key)


# ------------------------------------------------------------ off-switch
def test_resilience_enabled_is_hlo_instruction_identical(tmp_path):
    """The resilience hooks are all host-side: enabling the block leaves the
    compiled step program HLO-instruction-identical (acceptance: the async
    save never enters the graph)."""
    base = make_engine(simple_config(zero_optimization={"stage": 2}))
    res = make_engine(simple_config(
        zero_optimization={"stage": 2},
        resilience={"enabled": True, "save_dir": str(tmp_path),
                    "save_interval": 2}))
    assert res._resilience is not None
    xs, ys = batches(1)[0]
    h1 = optimized_hlo(base._jit_loss_and_grad, base.params,
                       base.scaler_state.cur_scale, xs, ys)
    h2 = optimized_hlo(res._jit_loss_and_grad, res.params,
                       res.scaler_state.cur_scale, xs, ys)
    assert h1 == h2
