"""Measured-time profile observatory: trace parsing, classification, interval
math, reconciliation verdicts, the diff gate, and the trace-dir namespacing
helper (docs/profile.md). Everything here is pure host work over the
committed fixture (tests/unit/fixtures/profile_cpu_mesh.trace.json.gz) or
synthetic inputs — the end-to-end traced engine run is gated by
``ds-tpu profile --reconcile`` in scripts/lint.sh against the committed
golden (tests/unit/golden/profile_reconcile.json)."""

import gzip
import json
import os

import pytest

from deepspeed_tpu.utils.profile_ingest import (
    ProfileParseError, device_slices, diff_reports, find_trace_files,
    is_collective_op, load_trace, load_trace_dir, program_profile_info,
    reconcile_profile, scan_trace_dirs, slice_level, slice_scope,
    stable_projection, summarize_slices, to_profile_trace_events)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "profile_cpu_mesh.trace.json.gz")

# the catalog row a profile-enabled compile would have recorded for the
# fixture's program (program_profile_info over the optimized HLO)
CATALOG = {
    "jit_loss_and_grad": {
        "program": "loss_and_grad",
        "scopes": {"fusion.1": "ds_fwd_bwd",
                   "reduce-scatter.6": "ds_grad_bucket0",
                   "all-gather.3": "ds_grad_bucket0"},
        "collectives": {
            "reduce-scatter.6": {"level": "dcn", "bytes": 1024, "bucket": 0},
            "all-gather.3": {"level": "ici", "bytes": 512, "bucket": 0},
        },
        "flops": 1000.0, "wire_ici": 512, "wire_dcn": 1024,
        "predicted_exposed_ici_us": 0.0, "predicted_exposed_dcn_us": 90.0,
    },
    "jit_apply_update": {
        "program": "apply_update",
        "scopes": {"fusion.3": "ds_apply_update"},
        "collectives": {},
        "flops": 200.0, "wire_ici": 0, "wire_dcn": 0,
        "predicted_exposed_ici_us": 0.0, "predicted_exposed_dcn_us": 0.0,
    },
}


def _slices():
    return device_slices(load_trace(FIXTURE)["traceEvents"])


# ----------------------------------------------------------------- parsing
def test_fixture_loads_and_filters_device_slices():
    """Only complete events carrying an hlo_op arg are device slices — the
    python host span and the counter event are dropped."""
    slices = _slices()
    assert len(slices) == 5
    assert all(s["module"].startswith("jit_") for s in slices)
    assert [s["op"] for s in slices] == [
        "fusion.1", "fusion.2", "reduce-scatter.6", "all-gather.3",
        "fusion.3"]


def test_malformed_trace_refused(tmp_path):
    """Truncated gzip, undecodable JSON, and a JSON object that is not a
    trace bundle all raise ProfileParseError with the path named — never a
    silent empty report, never a raw traceback type."""
    trunc = tmp_path / "t.trace.json.gz"
    trunc.write_bytes(gzip.compress(b'{"traceEvents": [')[:-4])
    with pytest.raises(ProfileParseError, match="t.trace.json.gz"):
        load_trace(str(trunc))
    bad = tmp_path / "bad.trace.json"
    bad.write_text("not json at all {")
    with pytest.raises(ProfileParseError, match="bad.trace.json"):
        load_trace(str(bad))
    wrong = tmp_path / "wrong.trace.json"
    wrong.write_text(json.dumps({"kind": "something_else"}))
    with pytest.raises(ProfileParseError, match="not a trace-viewer bundle"):
        load_trace(str(wrong))


def test_empty_trace_dir_refused(tmp_path):
    with pytest.raises(ProfileParseError, match="no trace files"):
        load_trace_dir(str(tmp_path))


def test_find_trace_files_walks_profiler_layout(tmp_path):
    d = tmp_path / "plugins" / "profile" / "2026_08_07_00_00_00"
    d.mkdir(parents=True)
    f = d / "vm.trace.json.gz"
    f.write_bytes(gzip.compress(json.dumps({"traceEvents": []}).encode()))
    assert find_trace_files(str(tmp_path)) == [str(f)]
    assert find_trace_files(str(f)) == [str(f)]


# ----------------------------------------------------------- classification
def test_collective_classification():
    assert is_collective_op("all-reduce.8")
    assert is_collective_op("reduce-scatter-start.2")
    assert is_collective_op("collective-permute.1")
    assert not is_collective_op("fusion.1")
    assert not is_collective_op("convert.3")


def test_scope_attribution_catalog_and_fallback():
    """The compile-time catalog is authoritative (CPU traces carry bare
    instruction names); TPU-style scope-prefixed op names attribute through
    the regex fallback with no catalog at all."""
    s = {"module": "jit_loss_and_grad", "op": "reduce-scatter.6",
         "ts": 0.0, "dur": 1.0}
    assert slice_scope(s, CATALOG) == "ds_grad_bucket0"
    assert slice_scope(s) is None
    tpu = {"module": "jit_train", "op": "ds_grad_bucket2/reduce-scatter.1",
           "ts": 0.0, "dur": 1.0}
    assert slice_scope(tpu) == "ds_grad_bucket2"
    assert slice_scope({"module": "m", "op": "ring_rot3/copy.1",
                        "ts": 0.0, "dur": 1.0}) == "ring_rot3"


def test_level_attribution():
    rs = {"module": "jit_loss_and_grad", "op": "reduce-scatter.6"}
    ag = {"module": "jit_loss_and_grad", "op": "all-gather.3"}
    assert slice_level(rs, CATALOG) == "dcn"
    assert slice_level(ag, CATALOG) == "ici"
    assert slice_level(rs) == "ici"  # no catalog: single-slice default


# -------------------------------------------------------------- window math
def test_window_interval_math():
    """The fixture is built for exact arithmetic: compute [0,150]+[300,340],
    DCN [140,240], ICI [200,260] -> exposed DCN 90 (not under compute),
    exposed ICI 20 (not under compute OR in-flight DCN), host gap 40."""
    report = summarize_slices(_slices(), catalog=CATALOG, devices=1, steps=1)
    cls = report["classes"]
    assert cls["compute"]["busy_us"] == 190.0          # [0,150] + [300,340]
    assert cls["collective_dcn"]["busy_us"] == 100.0
    assert cls["collective_dcn"]["exposed_us"] == 90.0
    assert cls["collective_ici"]["busy_us"] == 60.0
    assert cls["collective_ici"]["exposed_us"] == 20.0
    assert cls["host_gap"]["gap_us"] == 40.0           # extent 340 - union 300
    assert report["extent_us"] == 340.0
    assert report["step_wall_us"] == 340.0
    # per-bucket exposure: both fixture collectives are tagged bucket 0
    assert report["buckets"]["0"]["exposed_dcn_us"] == 90.0
    assert report["buckets"]["0"]["exposed_ici_us"] == 20.0


def test_scope_rows_and_programs():
    report = summarize_slices(_slices(), catalog=CATALOG, devices=1, steps=1,
                              peak_tflops=1e-6)
    scopes = report["scopes"]
    assert scopes["ds_fwd_bwd"]["slices"] == 1
    assert scopes["ds_grad_bucket0"]["collective_us"] == 160.0
    assert scopes["ds_apply_update"]["busy_us"] == 40.0
    assert scopes["unattributed"]["slices"] == 1       # fusion.2: no metadata
    progs = report["programs"]
    assert progs["jit_loss_and_grad"]["program"] == "loss_and_grad"
    assert progs["jit_loss_and_grad"]["flops"] == 1000.0
    # measured MFU: flops over the program's busy union [0,260] against peak
    assert progs["jit_loss_and_grad"]["measured_mfu"] == pytest.approx(
        1000.0 / (260e-6 * 1e-6 * 1e12))
    assert report["measured_mfu"] == pytest.approx(
        1200.0 / (340e-6 * 1e-6 * 1e12))


# ------------------------------------------------------------ reconciliation
def _derived(flops=1200.0, ici=512, dcn=1024, wall=None):
    return {"flops_per_step": flops, "wire_ici_per_step": ici,
            "wire_dcn_per_step": dcn, "step_wall_ms": wall}


def test_reconcile_ok_and_projection_excludes_wall_clock():
    measured = summarize_slices(_slices(), catalog=CATALOG, devices=1, steps=1)
    report = reconcile_profile(measured, CATALOG, _derived(wall=0.34),
                               entry="fixture")
    assert report["ok"]
    assert {c: r["status"] for c, r in report["classes"].items()} == {
        "compute": "ok", "collective_ici": "ok", "collective_dcn": "ok",
        "step_wall": "ok"}
    golden = stable_projection(report)
    assert "step_wall" not in golden["classes"]
    flat = json.dumps(golden)
    assert "_us" not in flat and "_ms" not in flat
    assert golden["classes"]["compute"]["predicted_flops_per_step"] == 1200.0
    assert golden["scopes_observed"] == [
        "ds_apply_update", "ds_fwd_bwd", "ds_grad_bucket0"]


def test_reconcile_drift_and_unobserved():
    measured = summarize_slices(_slices(), catalog=CATALOG, devices=1, steps=1)
    # derived flops 2x predicted -> compute drift, exit-1 contract
    drift = reconcile_profile(measured, CATALOG, _derived(flops=2400.0))
    assert not drift["ok"]
    assert drift["classes"]["compute"]["status"] == "drift"
    # a window that saw no slices at all: predictions exist, measurement
    # doesn't -> unobserved, not drift (and not ok)
    empty = summarize_slices([], catalog=CATALOG, devices=1, steps=1)
    rep = reconcile_profile(empty, CATALOG, _derived())
    assert rep["classes"]["compute"]["status"] == "unobserved"
    assert rep["classes"]["collective_ici"]["status"] == "unobserved"
    assert rep["classes"]["step_wall"]["status"] == "unobserved"


def test_diff_gate():
    measured = summarize_slices(_slices(), catalog=CATALOG, devices=1, steps=1)
    ok = stable_projection(
        reconcile_profile(measured, CATALOG, _derived(wall=0.34)))
    assert diff_reports(ok, ok)["ok"]
    # verdict regression ok -> drift is caught
    bad = json.loads(json.dumps(ok))
    bad["classes"]["compute"]["status"] = "drift"
    d = diff_reports(ok, bad)
    assert not d["ok"]
    assert any("compute" in r and "drift" in r for r in d["regressions"])
    # losing a scope from coverage is a regression too
    lost = json.loads(json.dumps(ok))
    lost["scopes_observed"].remove("ds_grad_bucket0")
    assert not diff_reports(ok, lost)["ok"]


# ---------------------------------------------------------------- trace dirs
def test_scan_trace_dirs_namespaced_and_legacy(tmp_path):
    (tmp_path / "trace_run-a_host0").mkdir()
    (tmp_path / "trace_run-a_host1").mkdir()
    (tmp_path / "trace_zzz_host0").mkdir()
    (tmp_path / "unrelated").mkdir()
    found = scan_trace_dirs(str(tmp_path))
    assert [(d["run"], d["host"]) for d in found] == [
        ("run-a", 0), ("run-a", 1), ("zzz", 0)]
    # legacy layout: the profiler wrote into trace_dir itself
    legacy = tmp_path / "old"
    (legacy / "plugins" / "profile" / "x").mkdir(parents=True)
    found = scan_trace_dirs(str(legacy))
    assert [(d["run"], d["host"]) for d in found] == [("", 0)]
    assert found[0]["path"] == str(legacy)
    assert scan_trace_dirs(str(tmp_path / "missing")) == []


# ------------------------------------------------------------- HLO catalog
HLO_TEXT = """\
HloModule jit_step, is_scheduled=true

ENTRY main {
  p0 = f32[8]{0} parameter(0)
  mul = f32[8]{0} multiply(p0, p0), metadata={op_name="jit(step)/jit(main)/ds_fwd_bwd/mul"}
  rs = f32[4]{0} reduce-scatter(mul), replica_groups={{0,1},{2,3}}, dimensions={0}, to_apply=add, metadata={op_name="jit(step)/jit(main)/ds_grad_bucket1/reduce-scatter"}
  ar = f32[4]{0} all-reduce(rs), replica_groups={{0,2},{1,3}}, to_apply=add, metadata={op_name="jit(step)/jit(main)/ds_grad_bucket1/all-reduce"}
  ROOT out = f32[4]{0} add(ar, ar)
}
"""


def test_program_profile_info_parses_scopes_and_levels():
    """The compile-time catalog: op_name metadata -> named scopes, replica
    groups against the slice factorization -> ICI vs DCN, bucket tags from
    the scope path."""
    info = program_profile_info(HLO_TEXT,
                                slice_sets=[{0, 1}, {2, 3}])
    assert info["module"] == "jit_step"
    assert info["scopes"]["mul"] == "ds_fwd_bwd"
    assert info["scopes"]["rs"] == "ds_grad_bucket1"
    # {{0,1},{2,3}} stays within the slices -> ICI; {{0,2},{1,3}} crosses
    assert info["collectives"]["rs"]["level"] == "ici"
    assert info["collectives"]["ar"]["level"] == "dcn"
    assert info["collectives"]["rs"]["bucket"] == 1
    # single-slice factorization: everything is ICI
    flat = program_profile_info(HLO_TEXT, slice_sets=None)
    assert flat["collectives"]["ar"]["level"] == "ici"


# ----------------------------------------------------------- merged timeline
def test_merged_timeline_tracks():
    """pid 0 = predicted schedule pinned above pid 1 = measured classes, and
    every measured slice lands on its class thread re-based to t0=0."""
    predicted = [{
        "name": "loss_and_grad",
        "roofline": {"compute_floor_s": 100e-6, "hbm_floor_s": 50e-6,
                     "mfu_ceiling": 0.5},
        "collectives": [
            {"op": "reduce-scatter", "level": "dcn", "instruction": "rs",
             "bytes": 1024, "async": True, "zero_overlap": False,
             "bucket": 0, "comm_s": 90e-6, "overlap_s": 40e-6,
             "exposed_s": 50e-6}],
    }]
    trace = to_profile_trace_events(_slices(), catalog=CATALOG,
                                    predicted_reports=predicted)
    evs = trace["traceEvents"]
    sort = {e["pid"]: e["args"]["sort_index"] for e in evs
            if e.get("name") == "process_sort_index"}
    assert sort == {0: 0, 1: 1}
    names = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert names[0] == "predicted schedule"
    assert names[1] == "measured trace"
    measured = [e for e in evs if e.get("ph") == "X" and e["pid"] == 1]
    assert len(measured) == 5
    assert min(e["ts"] for e in measured) == 0.0      # re-based to the window
    by_cat = {e["name"]: e["cat"] for e in measured}
    assert by_cat["reduce-scatter.6"] == "collective-dcn"
    assert by_cat["all-gather.3"] == "collective-ici"
    assert by_cat["fusion.1"] == "compute"
    predicted_evs = [e for e in evs if e.get("ph") == "X" and e["pid"] == 0]
    assert {e["cat"] for e in predicted_evs} == {"roofline", "exposed-comm"}
