"""The examples/ scripts stay runnable (subprocess smoke, CPU mesh, tiny steps).

The scripts themselves don't force a platform (on a TPU machine they use the
chip); here each runs under a bootstrap that pins the 8-device CPU platform
before the script body imports jax — same trick as tests/model/workload_env.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BOOTSTRAP = (
    "import sys, runpy;"
    f"sys.path.insert(0, {os.path.join(REPO, 'tests', 'model')!r});"
    "from workload_env import setup; setup();"
    "sys.argv = [sys.argv[1]] + sys.argv[2:];"
    "runpy.run_path(sys.argv[0], run_name='__main__')"
)


def _run_example(script, *args, timeout=600):
    r = subprocess.run(
        [sys.executable, "-c", BOOTSTRAP, os.path.join(REPO, "examples", script),
         *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout[-2000:]}\n{r.stderr[-2000:]}"
    return r.stdout


@pytest.mark.parametrize("extra", [
    (), pytest.param(("--zero", "3", "--sparse", "--seq", "128"),
                     marks=pytest.mark.slow)])  # ~26s subprocess; tier-1 cap
def test_train_gpt2_example(extra):
    out = _run_example("train_gpt2.py", "--steps", "3", "--layers", "2",
                       "--width", "64", "--vocab", "512", *extra)
    assert "greedy continuation:" in out


def test_train_bert_mlm_example():
    out = _run_example("train_bert_mlm.py", "--steps", "3", "--layers", "1",
                       "--hidden", "64", "--vocab", "256")
    assert "mlm loss" in out


def test_generate_text_example():
    out = _run_example("generate_text.py", "--new-tokens", "6", "--beams", "2")
    assert "greedy :" in out and "beam-2" in out
