"""Alert engine tests (docs/alerts.md).

The alert plane (utils/alerts.py) evaluates deterministic host-side rules
over the per-host metric ring on the end_step boundary — zero new device
syncs, and the compiled step programs are HLO-instruction-identical with the
plane on or off (pinned below for every train path AND the serving decode
programs). Covers: rule validation, the four rule kinds (threshold / delta /
stuck / slo_burn incl. burn-rate hysteresis), the fire/clear protocol through
SummaryMonitor + FlightRecorder (page severity dumps carry the full ring),
the fleet merge + assemble_cluster_report's alerts_fleet block, the CLI state
loaders, and the attribution harness against its committed golden.
"""

import glob
import json
import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils.alerts import (AlertEngine, default_rules,
                                        merge_fleet_alerts,
                                        run_alert_attribution, validate_rules,
                                        _load_alert_state)
from deepspeed_tpu.utils.metrics import MetricStore, default_catalog
from deepspeed_tpu.utils.monitor import SummaryMonitor
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden",
                      "alert_attribution.json")


# -------------------------------------------------------------- validation


def test_validate_rejects_malformed_rules():
    cat = default_catalog()
    with pytest.raises(ValueError, match="must be a list"):
        validate_rules("not-a-list")
    with pytest.raises(ValueError, match="kind must be one of"):
        validate_rules([{"name": "x", "kind": "gradient",
                         "metric": "Telemetry/Samples/mfu"}])
    with pytest.raises(ValueError, match="duplicate rule name"):
        validate_rules([{"name": "x", "kind": "threshold",
                         "metric": "Telemetry/Samples/mfu", "above": 1},
                        {"name": "x", "kind": "threshold",
                         "metric": "Telemetry/Samples/mfu", "above": 2}])
    with pytest.raises(ValueError, match="unknown key"):
        validate_rules([{"name": "x", "kind": "threshold",
                         "metric": "Telemetry/Samples/mfu", "above": 1,
                         "window": 4}])  # 'window' belongs to delta
    with pytest.raises(ValueError, match="needs 'above' and/or 'below'"):
        validate_rules([{"name": "x", "kind": "threshold",
                         "metric": "Telemetry/Samples/mfu"}])
    with pytest.raises(ValueError, match="budget"):
        validate_rules([{"name": "x", "kind": "slo_burn",
                         "metric": "Serving/Fleet/shed", "mode": "counter"}])
    with pytest.raises(ValueError, match="not declared"):
        validate_rules([{"name": "x", "kind": "threshold",
                         "metric": "Bogus/metric", "above": 1}], cat)
    # delta needs a direction to know which way is a regression
    with pytest.raises(ValueError, match="neutral"):
        validate_rules([{"name": "x", "kind": "delta",
                         "metric": "Train/Samples/lr"}], cat)


def test_validate_normalizes_defaults():
    rules = validate_rules([{"name": "d", "kind": "delta",
                             "metric": "Telemetry/Samples/mfu"}],
                           default_catalog())
    assert rules[0] == {"name": "d", "kind": "delta",
                        "metric": "Telemetry/Samples/mfu", "severity": "warn",
                        "window": 8, "baseline": 16, "drop_pct": 20.0}


def test_default_rules_cover_all_four_kinds():
    rules = default_rules()
    assert [r["kind"] for r in rules] == ["delta", "slo_burn", "stuck",
                                          "threshold"]
    assert {r["severity"] for r in rules} == {"warn", "page"}
    # every shipped rule targets a declared metric (validate enforces it,
    # but pin explicitly: the defaults ARE the PERF.md round-7 ruleset)
    cat = default_catalog()
    for r in rules:
        assert cat.resolve(r["metric"]) is not None, r["name"]


# ---------------------------------------------------------------- rule kinds


def _engine(rules, ring_len=64, monitor=None, recorder=None):
    store = MetricStore(ring_len=ring_len)
    eng = AlertEngine(rules=rules, store=store, monitor=monitor,
                      recorder=recorder)
    return eng, store


def test_threshold_for_steps_consecutive():
    eng, store = _engine([{"name": "hot", "kind": "threshold",
                           "metric": "Cluster/step_skew", "above": 3.0,
                           "for_steps": 2}])
    store.observe("Cluster/step_skew", 5.0, 0)
    assert eng.evaluate(0) == []          # one violating step is not enough
    store.observe("Cluster/step_skew", 1.0, 1)
    assert eng.evaluate(1) == []          # streak broken
    store.observe("Cluster/step_skew", 4.0, 2)
    eng.evaluate(2)
    store.observe("Cluster/step_skew", 4.5, 3)
    fired = eng.evaluate(3)               # two consecutive: fires
    assert [r["rule"] for r in fired] == ["hot"]
    assert fired[0]["detail"]["for_steps"] == 2
    assert eng.active() == ["hot"]


def test_delta_direction_comes_from_catalog():
    # higher-is-better metric: a DROP fires
    eng, store = _engine([{"name": "mfu", "kind": "delta",
                           "metric": "Telemetry/Samples/mfu",
                           "window": 2, "baseline": 2, "drop_pct": 20.0}])
    for step, v in enumerate((0.4, 0.4, 0.4, 0.4)):
        store.observe("Telemetry/Samples/mfu", v, step)
        assert eng.evaluate(step) == []
    store.observe("Telemetry/Samples/mfu", 0.28, 4)
    eng.evaluate(4)
    store.observe("Telemetry/Samples/mfu", 0.28, 5)
    fired = eng.evaluate(5)               # 30% below the baseline window
    assert [r["rule"] for r in fired] == ["mfu"]
    assert fired[0]["detail"]["regression_pct"] == pytest.approx(30.0)

    # lower-is-better metric: a RISE fires (same rule shape, inverted sign)
    eng2, store2 = _engine([{"name": "ttft", "kind": "delta",
                             "metric": "Serving/Latency/ttft_ms_p50",
                             "window": 2, "baseline": 2, "drop_pct": 20.0}])
    for step, v in enumerate((10.0, 10.0, 14.0, 14.0)):
        store2.observe("Serving/Latency/ttft_ms_p50", v, step)
        eng2.evaluate(step)
    assert [r["rule"] for r in eng2.fired] == ["ttft"]  # +40% latency


def test_stuck_pinned_at_value():
    eng, store = _engine([{"name": "ls", "kind": "stuck",
                           "metric": "Train/Samples/loss_scale",
                           "steps": 3, "at": 1.0}])
    # unchanged at a HEALTHY value: the pin means no fire
    for step in range(4):
        store.observe("Train/Samples/loss_scale", 256.0, step)
        assert eng.evaluate(step) == []
    # pinned to the min-scale floor for 3 steps: fires once
    for step in range(4, 8):
        store.observe("Train/Samples/loss_scale", 1.0, step)
        eng.evaluate(step)
    assert [r["rule"] for r in eng.fired] == ["ls"]
    assert eng.fired[0]["detail"]["mode"] == "unchanged"


def test_stuck_absent_mode_only_without_pin():
    # un-pinned rule: silence after an observation IS the failure
    eng, store = _engine([{"name": "hb", "kind": "stuck",
                           "metric": "Cluster/step_skew", "steps": 3}])
    store.observe("Cluster/step_skew", 1.1, 0)
    assert eng.evaluate(0) == []
    assert eng.evaluate(1) == []
    fired = eng.evaluate(3)               # 3 silent steps since step 0
    assert [r["rule"] for r in fired] == ["hb"]
    assert fired[0]["detail"]["mode"] == "absent"
    # pinned rule: absence never fires (it watches for a value, not silence)
    eng2, store2 = _engine([{"name": "ls", "kind": "stuck",
                             "metric": "Train/Samples/loss_scale",
                             "steps": 3, "at": 1.0}])
    store2.observe("Train/Samples/loss_scale", 256.0, 0)
    for step in range(12):
        assert eng2.evaluate(step) == []


def test_slo_burn_fraction_with_good_inversion():
    eng, store = _engine([{"name": "gp", "kind": "slo_burn",
                           "metric": "Serving/Fleet/Goodput/fraction",
                           "budget": 0.1, "good": True,
                           "fast_window": 2, "slow_window": 4,
                           "fast_burn": 3.0, "slow_burn": 2.0}])
    name = "Serving/Fleet/Goodput/fraction"
    for step in range(4):
        store.observe(name, 1.0, step)    # perfect goodput: zero burn
        assert eng.evaluate(step) == []
    for step in range(4, 8):
        store.observe(name, 0.6, step)    # bad fraction 0.4 = 4x budget
        eng.evaluate(step)
    assert [r["rule"] for r in eng.fired] == ["gp"]
    assert eng.fired[0]["detail"]["burn_fast"] == pytest.approx(4.0)


def test_slo_burn_hysteresis_no_flap():
    """Once firing, a burn alert clears only when BOTH windows are back
    within budget (burn < 1) — dipping just below the fire threshold on a
    bursty stream must NOT clear-and-refire."""
    rule = {"name": "shed", "kind": "slo_burn", "metric": "Serving/Fleet/shed",
            "mode": "counter", "budget": 1.0, "fast_window": 2,
            "slow_window": 4, "fast_burn": 3.0, "slow_burn": 2.0}
    eng, store = _engine([rule])
    total = 0.0
    deltas = [0, 0, 0, 0,            # healthy
              4, 4, 4, 4,            # burst: burn 4x budget -> fires once
              2, 2, 2, 2,            # still over budget, below fire bar:
                                     # hysteresis holds it ACTIVE (no flap)
              0, 0, 0, 0]            # back within budget: clears
    for step, d in enumerate(deltas):
        total += d
        store.observe("Serving/Fleet/shed", total, step)
        eng.evaluate(step)
    assert [r["rule"] for r in eng.fired] == ["shed"]     # exactly ONE firing
    state = eng._state["shed"]
    assert not state["active"] and state["fired"] == 1    # and it cleared


def test_slo_burn_counter_reset_clamps():
    """A counter reset (restart) steps the cumulative value DOWN — the
    per-step diff clamps at zero instead of registering negative burn."""
    eng, store = _engine([{"name": "shed", "kind": "slo_burn",
                           "metric": "Serving/Fleet/shed", "mode": "counter",
                           "budget": 1.0, "fast_window": 2, "slow_window": 4,
                           "fast_burn": 3.0, "slow_burn": 2.0}])
    for step, total in enumerate((100.0, 100.0, 100.0, 100.0, 0.0, 0.0)):
        store.observe("Serving/Fleet/shed", total, step)
        assert eng.evaluate(step) == []   # the reset is not an event storm


# ----------------------------------------------------------- fire protocol


def test_fire_once_then_clear_through_monitor(tmp_path):
    mon = SummaryMonitor(str(tmp_path), "al")
    eng, store = _engine([{"name": "hot", "kind": "threshold",
                           "metric": "Cluster/step_skew", "above": 3.0}],
                         monitor=mon)
    values = (5.0, 5.0, 5.0, 1.0)         # sustained violation, then healthy
    for step, v in enumerate(values):
        store.observe("Cluster/step_skew", v, step)
        eng.evaluate(step)
    mon.close()
    assert len(eng.fired) == 1            # one record, not one per step
    scalars = [json.loads(l) for l in
               open(os.path.join(str(tmp_path), "al", "scalars.jsonl"))]
    alert_scalars = [(s["step"], s["value"]) for s in scalars
                     if s["tag"] == "Alerts/hot"]
    assert alert_scalars == [(0, 1.0), (3, 0.0)]  # fire edge + clear edge
    events = [json.loads(l) for l in
              open(os.path.join(str(tmp_path), "al", "events.jsonl"))]
    kinds = [e["event"] for e in events]
    assert kinds == ["alert", "alert_clear"]
    assert events[0]["payload"]["rule"] == "hot"
    # snapshot is deterministic state, no wall clocks
    snap = eng.snapshot()
    assert snap["active"] == [] and len(snap["fired"]) == 1
    assert "time" not in json.dumps(snap)


def test_page_severity_dumps_the_ring(tmp_path):
    """page alerts trigger the flight recorder AFTER recording the firing,
    so the post-mortem bundle carries both the alert and the full ring."""
    from types import SimpleNamespace
    from deepspeed_tpu.utils.numerics import FlightRecorder
    store = MetricStore(ring_len=32)
    eng = AlertEngine(rules=[{"name": "hot", "kind": "threshold",
                              "metric": "Cluster/step_skew", "above": 3.0,
                              "severity": "page"}], store=store)
    tel = SimpleNamespace(monitor=None, watchdog=None,
                          alerts_snapshot=lambda: dict(eng.snapshot(),
                                                       ring=store.to_dict()))
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path), telemetry=tel)
    eng.recorder = rec
    store.observe("Cluster/step_skew", 9.0, 5)
    eng.evaluate(5)
    dumps = glob.glob(os.path.join(str(tmp_path), "*.json"))
    assert len(dumps) == 1
    bundle = json.load(open(dumps[0]))
    blk = bundle["alerts"]
    assert [r["rule"] for r in blk["fired"]] == ["hot"]
    assert blk["active"] == ["hot"]
    ring = blk["ring"]["series"]["Cluster/step_skew"]
    assert ring == [[5, 9.0]]
    # the CLI state loader reads the same dump
    state = _load_alert_state(dumps[0])
    assert [r["rule"] for r in state["fired"]] == ["hot"]


# ------------------------------------------------------- engine integration


def _build(**overrides):
    import jax
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))


def test_alerts_ride_end_step_through_the_real_engine(tmp_path):
    """Full wiring: telemetry.alerts config -> AlertEngine on the telemetry
    monitor + numerics flight recorder; a rule that must fire on step 1
    (step_time_ms above 0) emits the Alerts/* scalar, the alert event, and a
    page dump whose bundle embeds the alert state + metric ring."""
    rule = {"name": "any_step", "kind": "threshold",
            "metric": "Telemetry/Samples/step_time_ms", "above": 0.0,
            "severity": "page"}
    eng = _build(telemetry={"enabled": True, "output_path": str(tmp_path),
                            "job_name": "al",
                            "alerts": {"enabled": True, "rules": [rule]}},
                 numerics={"enabled": True, "dump_dir": str(tmp_path / "d")})
    assert eng.telemetry.alert_engine is not None
    assert eng.telemetry.alert_engine.recorder is not None
    xs, ys = _batch()
    for _ in range(2):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    eng.telemetry.close()
    fired = eng.telemetry.alert_engine.fired
    assert [r["rule"] for r in fired] == ["any_step"]
    scalars = open(os.path.join(str(tmp_path), "al", "scalars.jsonl")).read()
    assert "Alerts/any_step" in scalars
    state = _load_alert_state(os.path.join(str(tmp_path), "al",
                                           "events.jsonl"))
    assert [r["rule"] for r in state["fired"]] == ["any_step"]
    dumps = glob.glob(os.path.join(str(tmp_path), "d", "*.json"))
    assert dumps, "page alert produced no flight-recorder dump"
    bundle = json.load(open(dumps[0]))
    assert bundle["alerts"]["fired"][0]["rule"] == "any_step"
    assert "Telemetry/Samples/step_time_ms" in \
        bundle["alerts"]["ring"]["series"]


def test_alerts_require_telemetry():
    with pytest.raises(ValueError, match="telemetry.alerts.enabled requires"):
        _build(telemetry={"alerts": {"enabled": True}})


def test_bad_rule_fails_config_validation():
    with pytest.raises(ValueError, match="telemetry.alerts.rules"):
        _build(telemetry={"enabled": True,
                          "alerts": {"enabled": True,
                                     "rules": [{"name": "x",
                                                "kind": "gradient"}]}})


# ------------------------------------------------------------- fleet plane


def _host_snapshot(host, fire_step):
    store = MetricStore(ring_len=16, host=host)
    eng = AlertEngine(rules=[{"name": "hot", "kind": "threshold",
                              "metric": "Cluster/step_skew", "above": 3.0}],
                      store=store)
    for step in range(fire_step + 1):
        store.observe("Cluster/step_skew", 9.0 if step >= fire_step else 1.0,
                      step)
        eng.evaluate(step)
    return eng.snapshot()


def test_merge_fleet_alerts_names_first_firing_host():
    by_host = {1: {"alerts": _host_snapshot(1, 7)},
               0: {"alerts": _host_snapshot(0, 3)},
               2: {"alerts": None}}       # host with no alert plane: skipped
    merged = merge_fleet_alerts(by_host)
    assert merged["hosts"] == [0, 1, 2]
    assert merged["fired_total"] == 2
    assert merged["first_firing"] == {"host": 0, "rule": "hot", "step": 3,
                                      "severity": "warn"}
    assert merged["active"] == {"hot": [0, 1]}
    # deterministic regardless of dict insertion order
    assert merge_fleet_alerts(dict(sorted(by_host.items()))) == merged


def test_cluster_report_carries_alerts_fleet():
    from deepspeed_tpu.utils.cluster import assemble_cluster_report
    by_host = {0: {"alerts": _host_snapshot(0, 3)},
               1: {"alerts": _host_snapshot(1, 7)}}
    report = assemble_cluster_report(by_host, run_key="al")
    blk = report["alerts_fleet"]
    assert blk["first_firing"]["host"] == 0
    assert blk["fired_rules"] == ["hot"]
    # hosts without alert blocks -> no alerts_fleet (older dumps still merge)
    report2 = assemble_cluster_report({0: {}, 1: {}}, run_key="al")
    assert report2["alerts_fleet"] is None


# ------------------------------------------------------------- HLO identity


def test_train_step_paths_hlo_identical_with_alerts_on(tmp_path):
    """THE non-perturbation gate: the alert plane is host-side bookkeeping —
    every registered train program compiles to instruction-identical HLO
    with telemetry.alerts (and the metric catalog router) on."""
    import jax
    from deepspeed_tpu.utils.hlo import instruction_count, optimized_hlo
    model = SimpleModel(HIDDEN)
    engines = []
    for tel in (None, {"enabled": True, "output_path": str(tmp_path),
                       "metrics": {"enabled": True},
                       "alerts": {"enabled": True}}):
        over = dict(zero_optimization={"stage": 2})
        if tel:
            over["telemetry"] = tel
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
            config_params=simple_config(**over))
        engines.append(eng)
    eng_off, eng_on = engines
    batch = _batch()
    progs_off = {n: (j, a) for n, j, a, _m in eng_off.lint_programs(batch)}
    progs_on = {n: (j, a) for n, j, a, _m in eng_on.lint_programs(batch)}
    assert sorted(progs_off) == sorted(progs_on)
    for name in sorted(progs_off):
        h_off = optimized_hlo(*progs_off[name][0:1], *progs_off[name][1])
        h_on = optimized_hlo(*progs_on[name][0:1], *progs_on[name][1])
        assert instruction_count(h_off) > 0, name
        assert instruction_count(h_off) == instruction_count(h_on), name


def test_serving_decode_hlo_identical_with_alerts_on(tmp_path):
    """Same gate for the serving side: decode/prefill/beam programs of an
    engine whose telemetry session runs the alert plane are instruction-
    identical to one with no telemetry at all."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.serve.engine import InferenceEngine
    from deepspeed_tpu.utils.hlo import instruction_count, optimized_hlo
    from deepspeed_tpu.utils.telemetry import TelemetrySession
    ML = 32
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    session = TelemetrySession(output_path=str(tmp_path), job_name="al")
    session.configure_metrics()
    session.configure_alerts()
    kw = dict(num_slots=4, block_size=4, num_blocks=33, max_model_len=ML,
              prefill_chunk=8)
    eng_off = InferenceEngine(model, params, **kw)
    eng_on = InferenceEngine(model, params, telemetry=session, **kw)
    S, MB, C = eng_off.num_slots, eng_off.max_blocks, eng_off.prefill_chunk
    zs = jnp.zeros(S, jnp.int32)
    decode_args = (params, zs, zs, jnp.zeros((S, MB), jnp.int32),
                   jnp.zeros(S, bool), eng_off.k_pool, eng_off.v_pool)
    prefill_args = (params, jnp.zeros((1, C), jnp.int32), jnp.int32(0),
                    jnp.int32(1), jnp.zeros(MB, jnp.int32),
                    eng_off.k_pool, eng_off.v_pool)
    for name, a_fn, b_fn, fargs in (
            ("decode", eng_off._raw["decode_step"],
             eng_on._raw["decode_step"], decode_args),
            ("prefill", eng_off._raw["prefill_chunk"],
             eng_on._raw["prefill_chunk"], prefill_args)):
        h_off = optimized_hlo(a_fn, *fargs)
        h_on = optimized_hlo(b_fn, *fargs)
        assert instruction_count(h_off) > 0
        assert instruction_count(h_off) == instruction_count(h_on), name
    beam_off = eng_off._raw["beam_init"](4, -1)
    beam_on = eng_on._raw["beam_init"](4, -1)
    logits = jnp.zeros((1, model.config.vocab_size), jnp.float32)
    assert (instruction_count(optimized_hlo(beam_off, logits))
            == instruction_count(optimized_hlo(beam_on, logits))), "beam"
    session.close()


# ------------------------------------------------------ attribution harness


def test_attribution_harness_matches_golden(tmp_path):
    """The in-process harness must reproduce the committed golden exactly —
    the same transcript `ds-tpu alert-sim` golden-pins in lint.sh."""
    transcript = run_alert_attribution(dump_dir=str(tmp_path))
    golden = json.load(open(GOLDEN))
    assert transcript == golden
    assert transcript["ok"]
    # each scenario fired exactly its own rule; page scenarios dumped
    for s in transcript["scenarios"]:
        assert s["ok"], s["name"]
        assert [r["rule"] for r in s["fired"]] == [s["expected_rule"]]
    dumps = {s["name"]: s["dumps"] for s in transcript["scenarios"]}
    assert dumps["mfu_step_wall_inflation"] == 1        # page
    assert dumps["fleet_shed_poisson_2x"] == 1          # page
    assert dumps["loss_scale_forced_nan"] == 0          # warn: no dump
    assert dumps["heartbeat_dispatch_skew"] == 0        # warn: no dump
    # fleet attribution: host 0 (earlier injection) is named first-firing
    assert transcript["fleet"]["first_firing"]["host"] == 0
    assert transcript["fleet"]["first_firing"]["rule"] == "fleet_shed_burn"
