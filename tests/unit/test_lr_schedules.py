"""LR schedule semantics tests."""

import math

import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR,
                                                get_scheduler, VALID_LR_SCHEDULES)


class FakeOpt:
    def __init__(self, n_groups=1, lr=0.1):
        self.param_groups = [{"lr": lr, "betas": (0.9, 0.999)} for _ in range(n_groups)]


def test_warmup_lr():
    opt = FakeOpt()
    sched = WarmupLR(opt, warmup_min_lr=0.0, warmup_max_lr=0.01, warmup_num_steps=10)
    lrs = []
    for _ in range(15):
        sched.step()
        lrs.append(opt.param_groups[0]["lr"])
    assert lrs[0] < lrs[5] < lrs[9]
    assert lrs[9] == pytest.approx(0.01, rel=1e-6)
    assert lrs[14] == pytest.approx(0.01, rel=1e-6)  # constant after warmup


def test_warmup_decay_lr():
    opt = FakeOpt()
    sched = WarmupDecayLR(opt, total_num_steps=20, warmup_min_lr=0.0, warmup_max_lr=0.01,
                          warmup_num_steps=10)
    lrs = []
    for _ in range(20):
        sched.step()
        lrs.append(opt.param_groups[0]["lr"])
    assert lrs[9] == pytest.approx(0.01, rel=1e-6)
    assert lrs[19] < lrs[9]
    # at iteration 19: gamma = (total - iter) / (total - warmup) = (20-19)/10
    assert lrs[19] == pytest.approx(0.01 * (20 - 19) / 10, abs=1e-6)


def test_lr_range_test():
    opt = FakeOpt()
    sched = LRRangeTest(opt, lr_range_test_min_lr=0.001, lr_range_test_step_size=5,
                        lr_range_test_step_rate=1.0)
    assert opt.param_groups[0]["lr"] == pytest.approx(0.001)
    for _ in range(10):
        sched.step()
    # 10 step() calls from -1 land on iteration 9: lr = min_lr * (1 + 9/step_size)
    assert opt.param_groups[0]["lr"] == pytest.approx(0.001 * (1 + 9 / 5), rel=1e-6)


def test_lr_range_test_staircase():
    opt = FakeOpt()
    sched = LRRangeTest(opt, lr_range_test_min_lr=0.001, lr_range_test_step_size=5,
                        lr_range_test_step_rate=1.0, lr_range_test_staircase=True)
    for _ in range(4):
        sched.step()
    assert opt.param_groups[0]["lr"] == pytest.approx(0.001)  # still first stair
    for _ in range(5):
        sched.step()
    assert opt.param_groups[0]["lr"] == pytest.approx(0.002)


def test_one_cycle():
    opt = FakeOpt()
    sched = OneCycle(opt, cycle_min_lr=0.001, cycle_max_lr=0.01, cycle_first_step_size=10)
    lrs = []
    for _ in range(20):
        sched.step()
        lrs.append(opt.param_groups[0]["lr"])
    peak = max(lrs)
    assert peak == pytest.approx(0.01, rel=0.05)
    assert lrs[0] < peak
    assert lrs[-1] < peak


def test_one_cycle_momentum():
    opt = FakeOpt()
    sched = OneCycle(opt, cycle_min_lr=0.001, cycle_max_lr=0.01, cycle_first_step_size=10,
                     cycle_momentum=True, cycle_min_mom=0.8, cycle_max_mom=0.9)
    sched.step()
    beta0 = opt.param_groups[0]["betas"][0]
    assert 0.8 <= beta0 <= 0.9


def test_scheduler_state_roundtrip():
    opt = FakeOpt()
    sched = WarmupLR(opt, warmup_num_steps=10)
    for _ in range(7):
        sched.step()
    sd = sched.state_dict()
    sched2 = WarmupLR(FakeOpt(), warmup_num_steps=10)
    sched2.load_state_dict(sd)
    assert sched2.last_batch_iteration == sched.last_batch_iteration


def test_get_scheduler_by_name():
    for name in VALID_LR_SCHEDULES:
        opt = FakeOpt()
        kwargs = {}
        if name == "OneCycle":
            kwargs = {"cycle_min_lr": 0.001, "cycle_max_lr": 0.01}
        elif name == "WarmupDecayLR":
            kwargs = {"total_num_steps": 100}
        sched = get_scheduler(name, opt, kwargs)
        sched.step()
    with pytest.raises(ValueError):
        get_scheduler("NotASchedule", FakeOpt(), {})
