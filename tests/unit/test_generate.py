"""KV-cache autoregressive generation parity (greedy decode == full re-forward)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model


def _oracle_greedy(model, params, tokens, n_new):
    """Teacher-forcing oracle: re-run the FULL forward for every step and take
    argmax of the last position — what the cached decode must reproduce."""
    toks = np.asarray(tokens)
    for _ in range(n_new):
        logits = np.asarray(model.apply(params, jnp.asarray(toks)))
        nxt = np.argmax(logits[:, -1], axis=-1).astype(toks.dtype)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return toks


@pytest.mark.parametrize("moe", [False, True])
def test_greedy_generate_matches_full_forward(moe):
    cfg = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=3, n_head=2,
                     compute_dtype=jnp.float32,
                     **({"moe_experts": 4, "moe_every": 2,
                         "moe_capacity_factor": 8.0} if moe else {}))
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.default_rng(1).integers(0, 97, (2, 11)), jnp.int32)
    got = np.asarray(model.generate(params, prompt, max_new_tokens=8))
    want = _oracle_greedy(model, params, prompt, 8)
    np.testing.assert_array_equal(got, want)


def test_generate_sampling_and_bounds():
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(2))
    prompt = jnp.asarray(np.random.default_rng(3).integers(0, 64, (3, 5)), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=6, temperature=1.0,
                         rng=jax.random.PRNGKey(4))
    assert out.shape == (3, 11)
    o = np.asarray(out)
    assert ((o >= 0) & (o < 64)).all()
    np.testing.assert_array_equal(o[:, :5], np.asarray(prompt))
    # different rng -> (almost surely) different samples
    out2 = model.generate(params, prompt, max_new_tokens=6, temperature=1.0,
                          rng=jax.random.PRNGKey(5))
    assert not np.array_equal(np.asarray(out2), o)
    # single-token path
    one = model.generate(params, prompt, max_new_tokens=1)
    assert one.shape == (3, 6)


def test_top_k_samples_stay_in_the_top_k_set():
    """Teacher-forcing check: every sampled token must be among the top-k of the
    full-forward oracle logits for its prefix (and in the nucleus for top_p)."""
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(8))
    prompt = jnp.asarray(np.random.default_rng(9).integers(0, 64, (2, 4)), jnp.int32)
    k = 5
    out = np.asarray(model.generate(params, prompt, max_new_tokens=8, temperature=1.0,
                                    top_k=k, rng=jax.random.PRNGKey(10)))
    for t in range(4, 12):
        logits = np.asarray(model.apply(params, jnp.asarray(out[:, :t])))[:, -1]
        topk = np.argsort(logits, axis=-1)[:, -k:]
        for b in range(out.shape[0]):
            assert out[b, t] in topk[b], (b, t, out[b, t], topk[b])


def test_top_p_tiny_nucleus_is_greedy():
    """top_p small enough that only the argmax survives -> sampling == greedy,
    regardless of temperature; same for top_k=1."""
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(11))
    prompt = jnp.asarray(np.random.default_rng(12).integers(0, 64, (2, 4)), jnp.int32)
    greedy = np.asarray(model.generate(params, prompt, max_new_tokens=6))
    nucleus = np.asarray(model.generate(params, prompt, max_new_tokens=6,
                                        temperature=1.3, top_p=1e-6,
                                        rng=jax.random.PRNGKey(13)))
    np.testing.assert_array_equal(greedy, nucleus)
    topk1 = np.asarray(model.generate(params, prompt, max_new_tokens=6,
                                      temperature=0.7, top_k=1,
                                      rng=jax.random.PRNGKey(14)))
    np.testing.assert_array_equal(greedy, topk1)


def test_generate_reuses_compiled_programs():
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(6))
    prompt = jnp.asarray(np.random.default_rng(7).integers(0, 64, (2, 5)), jnp.int32)
    o1 = model.generate(params, prompt, max_new_tokens=4)
    assert len(model._gen_jit_cache) == 1
    o2 = model.generate(params, prompt, max_new_tokens=4)
    assert len(model._gen_jit_cache) == 1  # same signature -> same programs
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    with pytest.raises(AssertionError, match="max_new_tokens"):
        model.generate(params, prompt, max_new_tokens=0)
