"""KV-cache autoregressive generation parity (greedy decode == full re-forward)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model


def _oracle_greedy(model, params, tokens, n_new):
    """Teacher-forcing oracle: re-run the FULL forward for every step and take
    argmax of the last position — what the cached decode must reproduce."""
    toks = np.asarray(tokens)
    for _ in range(n_new):
        logits = np.asarray(model.apply(params, jnp.asarray(toks)))
        nxt = np.argmax(logits[:, -1], axis=-1).astype(toks.dtype)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return toks


@pytest.mark.parametrize("moe", [False, True])
def test_greedy_generate_matches_full_forward(moe):
    cfg = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=3, n_head=2,
                     compute_dtype=jnp.float32,
                     **({"moe_experts": 4, "moe_every": 2,
                         "moe_capacity_factor": 8.0} if moe else {}))
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.default_rng(1).integers(0, 97, (2, 11)), jnp.int32)
    got = np.asarray(model.generate(params, prompt, max_new_tokens=8))
    want = _oracle_greedy(model, params, prompt, 8)
    np.testing.assert_array_equal(got, want)


def test_generate_sampling_and_bounds():
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(2))
    prompt = jnp.asarray(np.random.default_rng(3).integers(0, 64, (3, 5)), jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=6, temperature=1.0,
                         rng=jax.random.PRNGKey(4))
    assert out.shape == (3, 11)
    o = np.asarray(out)
    assert ((o >= 0) & (o < 64)).all()
    np.testing.assert_array_equal(o[:, :5], np.asarray(prompt))
    # different rng -> (almost surely) different samples
    out2 = model.generate(params, prompt, max_new_tokens=6, temperature=1.0,
                          rng=jax.random.PRNGKey(5))
    assert not np.array_equal(np.asarray(out2), o)
    # single-token path
    one = model.generate(params, prompt, max_new_tokens=1)
    assert one.shape == (3, 6)


def test_top_k_samples_stay_in_the_top_k_set():
    """Teacher-forcing check: every sampled token must be among the top-k of the
    full-forward oracle logits for its prefix (and in the nucleus for top_p)."""
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(8))
    prompt = jnp.asarray(np.random.default_rng(9).integers(0, 64, (2, 4)), jnp.int32)
    k = 5
    out = np.asarray(model.generate(params, prompt, max_new_tokens=8, temperature=1.0,
                                    top_k=k, rng=jax.random.PRNGKey(10)))
    for t in range(4, 12):
        logits = np.asarray(model.apply(params, jnp.asarray(out[:, :t])))[:, -1]
        topk = np.argsort(logits, axis=-1)[:, -k:]
        for b in range(out.shape[0]):
            assert out[b, t] in topk[b], (b, t, out[b, t], topk[b])


def test_top_p_tiny_nucleus_is_greedy():
    """top_p small enough that only the argmax survives -> sampling == greedy,
    regardless of temperature; same for top_k=1."""
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(11))
    prompt = jnp.asarray(np.random.default_rng(12).integers(0, 64, (2, 4)), jnp.int32)
    greedy = np.asarray(model.generate(params, prompt, max_new_tokens=6))
    nucleus = np.asarray(model.generate(params, prompt, max_new_tokens=6,
                                        temperature=1.3, top_p=1e-6,
                                        rng=jax.random.PRNGKey(13)))
    np.testing.assert_array_equal(greedy, nucleus)
    topk1 = np.asarray(model.generate(params, prompt, max_new_tokens=6,
                                      temperature=0.7, top_k=1,
                                      rng=jax.random.PRNGKey(14)))
    np.testing.assert_array_equal(greedy, topk1)


def _teacher_forced_logprob(model, params, full, T0):
    """Sum of log p(token_t | prefix) over the generated suffix, fp32."""
    logits = np.asarray(model.logits(params, jnp.asarray(full[:, :-1]))
                        if hasattr(model, "logits") else
                        model.apply(params, jnp.asarray(full[:, :-1])))
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    tot = np.zeros(full.shape[0])
    for t in range(T0, full.shape[1]):
        for b in range(full.shape[0]):
            tot[b] += logp[b, t - 1, full[b, t]]
    return tot


def test_beam1_equals_greedy():
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(15))
    prompt = jnp.asarray(np.random.default_rng(16).integers(0, 64, (2, 5)), jnp.int32)
    greedy = np.asarray(model.generate(params, prompt, max_new_tokens=7))
    beam1, _ = model.beam_search(params, prompt, max_new_tokens=7, num_beams=1)
    np.testing.assert_array_equal(greedy, np.asarray(beam1))


def test_beam_search_scores_are_self_consistent_and_beat_greedy():
    """The returned score must equal the teacher-forced log-prob of the returned
    sequence (length_penalty=1 -> score*L), and the beam-4 winner's total
    log-prob must be >= the greedy sequence's."""
    cfg = GPT2Config(vocab_size=37, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(17))
    prompt = jnp.asarray(np.random.default_rng(18).integers(0, 37, (3, 4)), jnp.int32)
    L = 6
    seqs, scores = model.beam_search(params, prompt, max_new_tokens=L, num_beams=4)
    seqs = np.asarray(seqs)
    want = _teacher_forced_logprob(model, params, seqs, 4)
    np.testing.assert_allclose(np.asarray(scores) * L, want, rtol=1e-4, atol=1e-4)
    greedy = np.asarray(model.generate(params, prompt, max_new_tokens=L))
    g_lp = _teacher_forced_logprob(model, params, greedy, 4)
    assert (want >= g_lp - 1e-4).all(), (want, g_lp)


def test_beam_search_eos_freezes_and_pads():
    cfg = GPT2Config(vocab_size=16, n_positions=32, n_embd=16, n_layer=1, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(19))
    prompt = jnp.asarray(np.random.default_rng(20).integers(0, 16, (2, 3)), jnp.int32)
    seqs, scores = model.beam_search(params, prompt, max_new_tokens=8, num_beams=3,
                                     eos_token_id=5, length_penalty=0.8)
    seqs = np.asarray(seqs)
    assert seqs.shape == (2, 11) and np.isfinite(np.asarray(scores)).all()
    for b in range(2):
        gen = seqs[b, 3:]
        hits = np.where(gen == 5)[0]
        if hits.size:  # everything after the first EOS is EOS padding
            assert (gen[hits[0]:] == 5).all(), gen
    # normalized score self-consistency: raw log-prob accumulates only up to the
    # first EOS (frozen continuations are free), length counts it, clamped at L
    full_logits = np.asarray(model.logits(params, jnp.asarray(seqs[:, :-1])))
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(full_logits), axis=-1))
    for b in range(2):
        gen = seqs[b, 3:]
        hits = np.where(gen == 5)[0]
        n = min(int(hits[0]) + 1 if hits.size else 8, 8)
        raw = sum(logp[b, 3 - 1 + t, gen[t]] for t in range(n))
        want = raw / n ** 0.8
        np.testing.assert_allclose(float(scores[b]), want, rtol=1e-4, atol=1e-4)


def test_generate_reuses_compiled_programs():
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(6))
    prompt = jnp.asarray(np.random.default_rng(7).integers(0, 64, (2, 5)), jnp.int32)
    o1 = model.generate(params, prompt, max_new_tokens=4)
    assert len(model._gen_jit_cache) == 2  # shape-keyed prefill + decode
    o2 = model.generate(params, prompt, max_new_tokens=4)
    assert len(model._gen_jit_cache) == 2  # same signature -> same programs
    # a different sampling config compiles a new decode but REUSES the prefill
    model.generate(params, prompt, max_new_tokens=4, temperature=0.5,
                   rng=jax.random.PRNGKey(0))
    assert len(model._gen_jit_cache) == 3
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    with pytest.raises(AssertionError, match="max_new_tokens"):
        model.generate(params, prompt, max_new_tokens=0)
