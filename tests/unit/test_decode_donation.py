"""Decode cache programs must donate — the relay-kill crash regression pin.

The 1.5B-b8-decode / 420M-beam-4 relay kills (tests/perf/decode_crash_repro.py,
PR 2) were a cache double-buffer: the round-5 in-place ``dynamic_update_slice``
rewrite kept the caller's KV caches live across the prefill and decode programs
because nothing donated them, so XLA materialized input AND output cache
buffers (~5.7 GB each at 1.5B b8) through the prompt-forward activation peak —
over the 16 GB v5e cliff at execution time, which is why compilation succeeded
and the relay died mid-run. The fix donates the caches through prefill and both
decode programs and returns them, so XLA aliases one buffer input -> scan
carry -> output.

These tests pin the fix on CPU via the lint donation pass: every decode-path
program's declared cache donation must actually alias in the compiled HLO
(``unusable-donation``), no cache-sized input may ride un-donated
(``undonated-aliasable``), and the beam program's caches arrive pre-expanded
to [nl, B*K, ...] — the in-jit ``jnp.repeat`` variant is exactly the shape
mismatch that turns a donation into a silent no-op.
"""

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.lint.program_passes import ProgramArtifact, run_program_passes
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.utils import hlo

B, T0, L, K = 2, 4, 4, 2


@pytest.fixture(scope="module")
def artifacts():
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    progs = model.decode_lint_programs(params, batch=B, prompt_len=T0,
                                       max_new_tokens=L, num_beams=K)
    assert [n for n, _, _, _ in progs] == \
        ["gpt2_prefill", "gpt2_decode_greedy", "gpt2_decode_beam"]
    return {n: ProgramArtifact.capture(f"gpt2:{n}", jitted, args, manifest)
            for n, jitted, args, manifest in progs}


def test_every_decode_program_donates_exactly_its_caches(artifacts):
    for name, art in artifacts.items():
        donated = [i for i, (d, _, _) in enumerate(art.args_info) if d]
        assert len(donated) == 2, (name, donated)  # kcs, vcs and nothing else
        shapes = {art.args_info[i][1] for i in donated}
        assert len(shapes) == 1, (name, shapes)    # k and v caches match


def test_donated_caches_actually_alias_in_compiled_hlo(artifacts):
    """The donation must survive compilation as an input_output_alias entry —
    a declared-but-unaliased donation is the exact failure the crash had."""
    for name, art in artifacts.items():
        aliases = hlo.input_output_aliases(art.hlo_text)
        donated = [i for i, (d, _, _) in enumerate(art.args_info) if d]
        for i in donated:
            assert i in aliases, (name, i, sorted(aliases))
        assert not any("donated buffers were not usable" in w.lower()
                       for w in art.compile_warnings), (name, art.compile_warnings)


def test_beam_decode_caches_arrive_pre_expanded(artifacts):
    """Beam decode takes [nl, B*K, ...] caches (the eager repeat happens
    outside the jit); a [nl, B, ...] donated input cannot alias the
    [nl, B*K, ...] output and would be flagged unusable-donation."""
    art = artifacts["gpt2_decode_beam"]
    cache_shapes = [shape for d, shape, _ in art.args_info if d]
    assert all(s[1] == B * K for s in cache_shapes), cache_shapes


def test_decode_programs_pass_the_full_lint_suite(artifacts):
    """Donation clean, zero large collectives (single-host decode), and no
    dtype-promotion surprises — the same gate ds-tpu lint runs in CI."""
    violations = run_program_passes(artifacts.values())
    assert violations == [], [v.vid for v in violations]
