"""Guard: the tier-1 gate can never pick up tests/perf measurement scripts.

`scripts/tier1.sh` encodes the ROADMAP.md tier-1 command, which collects
`tests/` with pytest's default file patterns (``test_*.py`` / ``*_test.py``).
The perf scripts under tests/perf/ are benchmark drivers — minutes-to-hours of
wall clock, some requiring a real TPU — and keep deliberately non-matching
names so tier-1 never imports them. This suite pins both halves of that
contract: the script stays in sync with ROADMAP.md, and no file under
tests/perf/ matches a collectable pattern.
"""

import re
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]


def test_tier1_script_matches_roadmap_verbatim():
    roadmap = (REPO / "ROADMAP.md").read_text()
    m = re.search(r"\*\*Tier-1 verify:\*\* `(.+?)`\n", roadmap, re.DOTALL)
    assert m, "ROADMAP.md lost its 'Tier-1 verify:' line"
    script_lines = [ln for ln in (REPO / "scripts" / "tier1.sh").read_text().splitlines()
                    if ln and not ln.startswith("#")]
    assert script_lines == [m.group(1)], (
        "scripts/tier1.sh drifted from the ROADMAP.md tier-1 command — "
        "update them together, verbatim")


def test_perf_scripts_never_collected_by_tier1():
    perf = REPO / "tests" / "perf"
    offenders = [p.name for p in perf.glob("*.py")
                 if p.name.startswith("test_") or p.name.endswith("_test.py")]
    assert not offenders, (
        f"tests/perf/ files {offenders} match pytest's default collection "
        f"patterns and would run (or import-crash) inside the tier-1 gate — "
        f"rename them (the perf drivers are invoked directly, not collected)")


def test_serving_perf_driver_stays_out_of_tier1():
    """The serving benchmark (TPU-only, minutes of wall clock) must exist as
    a direct-invocation driver and never under a collectable name."""
    perf = REPO / "tests" / "perf"
    assert (perf / "serving_perf.py").exists()
    assert not (perf / "test_serving_perf.py").exists(), (
        "serving perf driver must not be collectable — tier-1 would sys.exit "
        "on the CPU mesh")


def test_request_trace_suite_is_collectable_and_golden_pinned():
    """The serving observatory's acceptance tests live INSIDE tier-1 (CPU-only,
    seconds of wall clock), so the suite file must match a collectable name and
    its byte-for-byte golden must ship next to the pipeline-trace goldens."""
    unit = REPO / "tests" / "unit"
    assert (unit / "test_request_trace.py").exists()
    golden = unit / "golden" / "serve_timeline_64.trace.json"
    assert golden.exists(), "serve-timeline golden missing — regenerate with " \
        "`ds-tpu serve-sim --no-mirror --dump-ledger L.json && " \
        "ds-tpu serve-timeline L.json -o <golden>`"
    import json
    trace = json.loads(golden.read_text())
    assert trace["otherData"]["generator"] == "ds-tpu serve-timeline"
    assert len(trace["traceEvents"]) > 1000    # a real 64-request timeline


def test_perf_directory_has_no_conftest_collection_override():
    """A conftest.py in tests/perf/ could re-add collection via collect_ignore
    tricks or python_files overrides; keep the directory plugin-free."""
    ini_like = [p.name for p in (REPO / "tests" / "perf").glob("conftest.py")]
    assert not ini_like, "tests/perf/conftest.py could alter tier-1 collection"
