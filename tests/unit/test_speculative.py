"""Speculative decoding over the paged KV pool: identity and rollback.

Speculation must be a *step-count* change, not an output change: a request
served with draft-model drafting + one-step batched verification emits the
SAME tokens as plain greedy decode (which itself matches the model's own
monolithic ``generate``), while executing strictly fewer target-model
programs. Also pinned here: the pure acceptance rule, rejection rollback as
a free block-table truncation (with a CoW no-alias proof — forked snapshot
pages stay bitwise frozen while the speculating writer advances), rejection
at position 0 degenerating to exactly one committed token, prefix-cache
interaction, preemption/warm-restart transparency, the zero-recompile
contract for all three spec programs, and the mirror-oracle refusal.

Engines are expensive to build (each compiles its program set), so the
standard-geometry speculative and plain engines are module-scoped and shared
by the tests that can reuse them; step/acceptance counters are compared as
deltas. Reference prompts stick to max_new_tokens=6 so ``generate`` compiles
one decode program per prompt length for the whole module.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.engine import InferenceEngine
from deepspeed_tpu.serve.scheduler import Request
from deepspeed_tpu.serve.sim import synth_trace
from deepspeed_tpu.serve.speculative import accept_greedy
from deepspeed_tpu.utils.telemetry import TelemetrySession

ML = 32
L = 6          # shared max_new_tokens: one generate decode program per shape


@pytest.fixture(scope="module")
def model_and_params():
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_and_params, *, speculate=True, draft_seed=None, spec_k=4,
            **kw):
    """Engine factory; ``draft_seed=None`` self-drafts (acceptance ~1 by
    construction), an int redraws draft params so verification rejects."""
    model, params = model_and_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 33)
    kw.setdefault("max_model_len", ML)
    kw.setdefault("prefill_chunk", 8)
    if speculate:
        dparams = (params if draft_seed is None
                   else model.init(jax.random.PRNGKey(draft_seed)))
        kw["speculation"] = {"enabled": True, "draft_model": model,
                             "draft_params": dparams,
                             "max_draft_tokens": spec_k}
    return InferenceEngine(model, params, **kw)


@pytest.fixture(scope="module")
def spec_engine(model_and_params):
    """Shared standard-geometry self-draft engine — every test that uses it
    drains it back to idle."""
    return _engine(model_and_params)


@pytest.fixture(scope="module")
def plain_engine(model_and_params):
    return _engine(model_and_params, speculate=False)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(
        0, 64, size=n).astype(np.int32).tolist()


def _reference(model_and_params, prompt, max_new=L):
    model, params = model_and_params
    ref = model.generate(params, jnp.asarray([prompt], jnp.int32), max_new)
    return np.asarray(ref)[0, len(prompt):].tolist()


def test_accept_greedy_rule():
    # full accept: all m drafts match, the bonus row commits too -> m+1 tokens
    committed, a = accept_greedy([5, 6, 7, 8, 9], [5, 6, 7, 8])
    assert committed == [5, 6, 7, 8, 9] and a == 4
    # first mismatch stops the walk; the mismatching row's argmax still
    # commits (it IS the plain-decode token at that position)
    committed, a = accept_greedy([5, 9, 7], [5, 6])
    assert committed == [5, 9] and a == 1
    # rejection at position 0 degenerates to plain decode: one token, row 0
    committed, a = accept_greedy([7, 1, 2], [5, 6])
    assert committed == [7] and a == 0
    # no drafts: the rule is exactly one plain decode step
    committed, a = accept_greedy([3], [])
    assert committed == [3] and a == 0


def test_self_draft_matches_generate_with_strictly_fewer_steps(
        model_and_params, spec_engine, plain_engine):
    reqs = [Request(f"sd{i}", _prompt(50 + i, 7 + i), L) for i in range(4)]
    steps0, ss0 = spec_engine.target_steps, spec_engine.spec_summary()
    outs_spec, _ = spec_engine.run([Request(r.req_id, list(r.prompt), L)
                                    for r in reqs])
    psteps0 = plain_engine.target_steps
    outs_plain, _ = plain_engine.run([Request(r.req_id, list(r.prompt), L)
                                      for r in reqs])

    for r, o in zip(reqs, outs_spec[-4:]):
        assert o.status == "finished"
        assert o.tokens == _reference(model_and_params,
                                      list(r.prompt)), r.req_id
    assert ([o.tokens for o in outs_spec[-4:]]
            == [o.tokens for o in outs_plain[-4:]])
    # the headline contract: token-identical output from strictly fewer
    # target-model program executions (deltas — the engines are shared)
    assert (spec_engine.target_steps - steps0
            < plain_engine.target_steps - psteps0)
    ss = spec_engine.spec_summary()
    drafted = ss["drafted_tokens"] - ss0["drafted_tokens"]
    accepted = ss["accepted_tokens"] - ss0["accepted_tokens"]
    assert drafted == accepted > 0                    # self-draft: all accept
    assert ss["spec_acceptance_rate"] == 1.0
    assert ss["target_steps_per_token"] < 1.0


def test_rejections_roll_back_tables_and_stay_token_identical(
        model_and_params):
    """A draft with different weights gets rejected: every rejection must
    truncate the target block table back to the committed frontier (the
    invariant below fails if the tail pages leak), at least one round must
    reject at position 0 (committing exactly one token — plain decode's
    step), and the emitted streams must still match ``model.generate``."""
    reqs = [Request(f"r{i}", _prompt(60 + i, 7 + i), L) for i in range(4)]
    eng = _engine(model_and_params, draft_seed=2)
    alloc = eng.scheduler.allocator
    for r in reqs:
        eng.submit(Request(r.req_id, list(r.prompt), L))
    spec_entries = []
    while not eng.scheduler.idle:
        log = eng.step()
        spec_entries.extend(log.get("spec") or [])
        for g in eng.scheduler.running:
            if g.phase != "decode":
                continue
            for ln in range(g.lanes):
                # the table never covers past the next write block: rollback
                # released every page beyond the accepted frontier
                assert len(g.tables[ln]) <= alloc.blocks_for_tokens(
                    g.next_pos(ln) + 1)

    assert any(a < m for _, m, a, _ in spec_entries), "no rejection occurred"
    assert any(a == m for _, m, a, _ in spec_entries), "no full accept"
    assert any(a == 0 and c == 1 for _, m, a, c in spec_entries), \
        "no position-0 rejection (should commit exactly the plain token)"
    for r in reqs:
        assert eng.outputs[r.req_id].tokens == _reference(
            model_and_params, list(r.prompt)), r.req_id
    ss = eng.spec_summary()
    assert 0 < ss["spec_acceptance_rate"] < 1.0
    assert ss["wasted_draft_tokens"] == (ss["drafted_tokens"]
                                         - ss["accepted_tokens"]) > 0
    # both pools drain: rollback freed the rejected tails, finish freed the rest
    assert alloc.num_used == 0
    assert eng._spec.pool_stats()["used"] == 0


def test_cow_rollback_never_aliases_a_forked_snapshot(model_and_params,
                                                      spec_engine):
    """Fork a mid-decode request's block table (an external share-holder,
    e.g. a warm-restart snapshot) and keep decoding speculatively: every
    verify write into the shared extent must go through ensure_exclusive
    (CoW), so the forked pages' KV bytes stay bitwise frozen while the
    request's own stream is unaffected — rollback and commit operate on
    copies, never in place."""
    prompt = _prompt(70, 9)
    eng = spec_engine
    alloc = eng.scheduler.allocator
    eng.submit(Request("f0", list(prompt), L))
    for _ in range(12):
        eng.step()
        running = [g for g in eng.scheduler.running if g.phase == "decode"]
        if running and len(running[0].generated[0]) >= 1:
            break
    else:
        pytest.fail("request never observed mid-decode")
    g = running[0]
    snap = alloc.fork(g.tables[0])          # share every page, incl. partial
    cow_before = alloc.cow_copies
    before = np.asarray(eng.k_pool)[:, snap].copy()
    while not eng.scheduler.idle:
        eng.step()
    after = np.asarray(eng.k_pool)[:, snap]
    assert np.array_equal(before, after), \
        "a verify/decode write mutated a shared (forked) KV page in place"
    assert alloc.cow_copies > cow_before    # the share forced real copies
    assert eng.outputs["f0"].tokens == _reference(model_and_params, prompt)
    alloc.free(snap)
    assert alloc.num_used == 0


def test_prefix_cache_interaction(model_and_params, plain_engine):
    """Speculation composes with the prefix cache: blocks filled under
    speculative commits still park/register on release, a second wave with
    the same system prompt hits them, and outputs stay identical to a
    cache-off, speculation-off engine."""
    shared = _prompt(80, 12)
    def wave(tag):
        return [Request(f"{tag}{i}", shared + _prompt(90 + i, 3), 5)
                for i in range(3)]
    eng = _engine(model_and_params, prefix_cache=True)
    eng.run(wave("a"))
    eng.run(wave("b"))
    assert eng.prefix_cache.stats()["hit_tokens"] > 0

    plain_engine.run(wave("a"))
    for i in range(3):
        assert (eng.outputs[f"a{i}"].tokens
                == plain_engine.outputs[f"a{i}"].tokens)
        assert eng.outputs[f"b{i}"].tokens == eng.outputs[f"a{i}"].tokens


def test_preemption_mid_burst_restores_identical_tokens(model_and_params,
                                                        plain_engine):
    """Starving the pool preempts speculating requests mid-burst (draft state
    dropped, target pages released, full-restart recompute) — outputs must
    equal an un-starved speculation-off engine's exactly."""
    reqs = [Request(f"p{i}", _prompt(100 + i, 9), L) for i in range(4)]
    small = _engine(model_and_params, num_blocks=13)
    outs_small, _ = small.run([Request(r.req_id, list(r.prompt), L)
                               for r in reqs])
    plain_engine.run([Request(r.req_id, list(r.prompt), L) for r in reqs])
    assert sum(o.preemptions for o in outs_small) > 0
    for r in reqs:
        assert (small.outputs[r.req_id].tokens
                == plain_engine.outputs[r.req_id].tokens), r.req_id
    assert small._spec.pool_stats()["used"] == 0


def test_warm_restart_mid_burst_token_identity(model_and_params, spec_engine,
                                               plain_engine):
    """state_dict() mid-burst drops draft state (best-effort by design); the
    restored replica re-drafts from committed context and the outputs still
    match a speculation-off run."""
    reqs = [Request(f"w{i}", _prompt(110 + i, 7), L) for i in range(4)]
    a = _engine(model_and_params)
    for r in reqs:
        a.submit(Request(r.req_id, list(r.prompt), L))
    for _ in range(6):                      # mid-burst: some decode progress
        a.step()
    state = a.state_dict()
    assert a._spec.pool_stats()["used"] == 0    # drop_all ran

    b = spec_engine                         # same geometry; idle, reusable
    b.load_state_dict(state)
    while not b.scheduler.idle:
        b.step()
    plain_engine.run([Request(r.req_id, list(r.prompt), L) for r in reqs])
    for r in reqs:
        assert (b.outputs[r.req_id].tokens
                == plain_engine.outputs[r.req_id].tokens), r.req_id


def test_zero_recompiles_all_spec_programs(model_and_params):
    """The mixed greedy/beam/sampled trace exercises drafting, verification
    and the ride-along lanes — every spec program (target verify + draft
    decode/prefill) must compile exactly once."""
    from deepspeed_tpu.utils.monitor import SummaryMonitor
    session = TelemetrySession(monitor=SummaryMonitor(enabled=False))
    eng = _engine(model_and_params, telemetry=session)
    reqs = synth_trace(10, vocab_size=64, max_model_len=ML, seed=3)
    outs, _ = eng.run(reqs)
    assert all(o.status == "finished" for o in outs)
    assert eng.spec_summary()["spec_rounds"] > 0

    served = [n for n in session.watchdog.records if n.startswith("serve:")]
    for name in ("serve:spec_verify", "serve:spec_draft_decode",
                 "serve:spec_draft_prefill"):
        assert name in served, name
    for name in served:
        assert session.watchdog.compiles(name) == 1, name
        assert session.watchdog.recompiles(name) == 0, name


def test_paged_program_cache_shared_across_engines(model_and_params,
                                                   spec_engine, plain_engine):
    """Engines over the same model and geometry share one program set (the
    build memo in serve/paged.py) — a warm restart or test fleet pays XLA
    once per process. Different geometry (here: speculation on/off, which
    changes verify_width) still builds its own."""
    eng = _engine(model_and_params)
    assert eng._raw is spec_engine._raw
    assert eng._raw is not plain_engine._raw
    model, params = model_and_params
    other = _engine((model, params), speculate=False)
    assert other._raw is plain_engine._raw


def test_mirror_oracle_refused_with_speculation(model_and_params):
    """The D-wide verify is argmax-identical but not bitwise-identical to the
    1-wide decode step (ulp fusion drift), so the bitwise mirror oracle and
    speculation are mutually exclusive — refuse loudly, don't fail the
    bitwise assert mysteriously later."""
    with pytest.raises(ValueError, match="mirror"):
        _engine(model_and_params, mirror=True)
