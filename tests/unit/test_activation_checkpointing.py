"""Activation checkpointing tests (reference had no dedicated unit tests for
checkpointing.py — its coverage came from Megatron model tests; here we test grad
parity, offload policy, partitioned saveables, and the RNG parity API directly)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import MODEL_AXIS, build_mesh, set_mesh
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ckpt


@pytest.fixture(autouse=True)
def _reset():
    ckpt.reset()
    yield
    ckpt.reset()


def _block(x, w):
    return jnp.tanh(x @ w) @ w.T


def _loss(fn, x, w):
    return jnp.sum(fn(x, w) ** 2)


def _grads(fn, x, w):
    return jax.jit(jax.grad(lambda xx, ww: _loss(fn, xx, ww), argnums=(0, 1)))(x, w)


@pytest.fixture
def xw():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return jax.random.normal(k1, (8, 16)), jax.random.normal(k2, (16, 16)) * 0.1


def test_checkpoint_grad_parity(xw):
    x, w = xw
    ckpt.configure()
    ref = _grads(_block, x, w)
    got = _grads(ckpt.checkpoint_wrapper(_block), x, w)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(r), np.asarray(g), rtol=1e-6)


def test_checkpoint_call_style(xw):
    """reference call style: checkpoint(function, *args) (checkpointing.py:739)."""
    x, w = xw
    out = ckpt.checkpoint(_block, x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_block(x, w)), rtol=1e-6)


def test_cpu_checkpointing_grad_parity(xw):
    x, w = xw
    ckpt.configure(checkpoint_in_cpu=True)
    assert ckpt.is_configured()
    ref = _grads(_block, x, w)
    got = _grads(ckpt.checkpoint_wrapper(_block), x, w)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(r), np.asarray(g), rtol=1e-6)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs multi-device mesh")
def test_partition_activations_grad_parity(xw):
    x, w = xw
    mesh = build_mesh(data=2, model=4, pipe=1) if len(jax.devices()) == 8 else \
        build_mesh(data=1, model=len(jax.devices()), pipe=1)
    ckpt.configure(partition_activations=True, mesh=mesh)
    ref = _grads(_block, x, w)
    with set_mesh(mesh):
        got = _grads(ckpt.checkpoint_wrapper(_block), x, w)
    for r, g in zip(ref, got):
        # sharded matmul reduction order shifts the last few ulps
        np.testing.assert_allclose(np.asarray(r), np.asarray(g), rtol=1e-4, atol=1e-6)


def test_configure_from_deepspeed_config():
    cfg = deepspeed_tpu.DeepSpeedConfig(
        {"train_batch_size": 8,
         "activation_checkpointing": {"partition_activations": True,
                                      "cpu_checkpointing": True,
                                      "number_checkpoints": 4,
                                      "profile": True}},
        world_size=1)
    ckpt.configure(deepspeed_config=cfg)
    assert ckpt._config["partition_activations"] is True
    assert ckpt._config["cpu_checkpointing"] is True
    assert ckpt._config["number_checkpoints"] == 4
    assert ckpt._config["profile"] is True


def test_profile_mode_runs(xw):
    x, w = xw
    ckpt.configure(profile=True)
    got = _grads(ckpt.checkpoint_wrapper(_block), x, w)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in got)


def test_rng_tracker_streams():
    tracker = ckpt.get_rng_tracker()
    tracker.reset()
    tracker.add("model-parallel-rng", 42)
    a = tracker.fork()
    b = tracker.fork()
    assert not np.array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError):
        tracker.add("model-parallel-rng", 1)
    with pytest.raises(KeyError):
        tracker.fork("nope")
    # replay determinism: same seed → same stream
    tracker.reset()
    tracker.add("model-parallel-rng", 42)
    a2 = tracker.fork()
    np.testing.assert_array_equal(np.asarray(a), np.asarray(a2))


def test_model_parallel_manual_seed_parity_api():
    ckpt.model_parallel_cuda_manual_seed(1234)
    t = ckpt.get_cuda_rng_tracker()
    assert "model-parallel-rng" in t.get_states() and "data-parallel-rng" in t.get_states()


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs multi-device mesh")
def test_model_parallel_seed_differs_per_rank():
    from deepspeed_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = build_mesh(data=1, model=len(jax.devices()), pipe=1)

    def f():
        key = ckpt.model_parallel_seed(7, axis=MODEL_AXIS)
        return jax.random.uniform(key, (1,))

    with set_mesh(mesh):
        out = jax.jit(shard_map(f, mesh=mesh, in_specs=(), out_specs=P(MODEL_AXIS),
                                check_vma=False))()
    vals = np.asarray(out)
    assert len(np.unique(vals)) == len(vals), "per-rank dropout keys must differ"


def test_gpt2_remat_uses_config(xw):
    """GPT-2 remat path goes through checkpoint_wrapper and trains identically."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4, remat=True)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tok = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)))
    loss_remat = jax.jit(lambda p: model.apply(p, tok[:, :-1], tok[:, 1:]))(params)

    cfg2 = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4, remat=False)
    loss_plain = jax.jit(lambda p: GPT2Model(cfg2).apply(p, tok[:, :-1], tok[:, 1:]))(params)
    np.testing.assert_allclose(float(loss_remat), float(loss_plain), rtol=1e-5)


@pytest.mark.slow  # engine+offload-remat compile (~11s); tier-1 870s cap
def test_engine_composes_with_cpu_checkpointing():
    """regression: offload-remat custom-calls must not collide with the engine's
    out_shardings (XLA SPMD 'side-effect ops cannot be replicated')."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4, remat=True)
    model = GPT2Model(cfg)
    ds_cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 2},
              "activation_checkpointing": {"cpu_checkpointing": True,
                                           "partition_activations": True}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)), config_params=ds_cfg)
    tok = jnp.asarray(np.random.default_rng(0).integers(0, 64, (8, 17)))
    losses = []
    for _ in range(4):
        loss = engine.forward(tok[:, :-1], tok[:, 1:])
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]
