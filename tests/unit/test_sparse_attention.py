"""Block-sparse attention tests (parity with reference tests/unit/test_sparse_attention.py
strategy: kernel vs dense equivalents, layout properties, utils)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                                                DenseSparsityConfig, FixedSparsityConfig,
                                                SparseAttentionUtils, SparseSelfAttention,
                                                BertSparseSelfAttention, VariableSparsityConfig)
from deepspeed_tpu.ops.pallas.block_sparse_attention import (block_sparse_attention, build_luts,
                                                             dense_blocksparse_attention)

B, H, T, D, BLOCK = 2, 4, 256, 32, 32


def qkv(seed=0, shape=(B, H, T, D)):
    return tuple(jax.random.normal(k, shape, jnp.float32)
                 for k in jax.random.split(jax.random.PRNGKey(seed), 3))


# ---------------- layout properties ----------------

def test_dense_layout_all_ones():
    layout = DenseSparsityConfig(num_heads=H, block=BLOCK).make_layout(T)
    assert layout.shape == (H, T // BLOCK, T // BLOCK)
    assert layout.all()


def test_fixed_layout_properties():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4, num_global_blocks=1)
    layout = cfg.make_layout(T)
    nb = T // BLOCK
    # local windows are dense within themselves
    for w in range(0, nb, 4):
        assert layout[0, w:w + 4, w:w + 4].all()
    # single layout propagated to all heads
    assert (layout == layout[0]).all()
    # global column (last block of each window) attended by everyone
    assert layout[0, :, 3].all()


def test_fixed_unidirectional_upper_triangle_empty():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(T)
    nb = T // BLOCK
    for r in range(nb):
        assert not layout[0, r, r + 1:].any()


def test_fixed_different_layout_per_head():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, different_layout_per_head=True,
                              num_local_blocks=4, num_global_blocks=1,
                              num_different_global_patterns=4)
    layout = cfg.make_layout(T)
    assert not (layout[0] == layout[1]).all()


def test_bigbird_layout_properties():
    cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                                num_sliding_window_blocks=3, num_global_blocks=1)
    layout = cfg.make_layout(T)
    nb = T // BLOCK
    assert layout[0, 0, :].all() and layout[0, :, 0].all()  # global first block
    for r in range(1, nb - 1):
        assert layout[0, r, r - 1:r + 2].all()  # sliding window


def test_bslongformer_layout_properties():
    cfg = BSLongformerSparsityConfig(num_heads=H, block=BLOCK, num_sliding_window_blocks=3,
                                     global_block_indices=[0, 2])
    layout = cfg.make_layout(T)
    assert layout[0, 2, :].all() and layout[0, :, 2].all()


def test_variable_layout_global_ranges():
    cfg = VariableSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=0,
                                 local_window_blocks=[2, 4],
                                 global_block_indices=[0], global_block_end_indices=[2])
    layout = cfg.make_layout(T)
    assert layout[0, :, 0].all() and layout[0, :, 1].all()


def test_layout_seq_not_divisible_raises():
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=H, block=BLOCK).make_layout(T + 7)


def test_invalid_configs_raise():
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=H, num_local_blocks=4, num_global_blocks=3)
    with pytest.raises(NotImplementedError):
        FixedSparsityConfig(num_heads=H, attention="sideways")
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=H, attention="unidirectional",
                            horizontal_global_attention=True)
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=H, num_different_global_patterns=2)


def test_build_luts_roundtrip():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    layout = cfg.make_layout(T)
    counts, cols, counts_t, rows_t = build_luts(layout)
    nb = T // BLOCK
    for h in range(H):
        for i in range(nb):
            active = set(np.nonzero(layout[h, i])[0])
            assert set(cols[h * nb + i, :counts[h * nb + i]]) == active


# ---------------- kernel parity ----------------

@pytest.mark.parametrize("pattern", ["fixed", "fixed_uni", "bigbird", "bslongformer", "variable"])
def test_kernel_parity(pattern):
    causal = False
    if pattern == "fixed":
        cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    elif pattern == "fixed_uni":
        cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4,
                                  attention="unidirectional")
        causal = True
    elif pattern == "bigbird":
        cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK)
    elif pattern == "bslongformer":
        cfg = BSLongformerSparsityConfig(num_heads=H, block=BLOCK)
    else:
        cfg = VariableSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1)
    layout = cfg.make_layout(T)
    q, k, v = qkv()
    out_s = block_sparse_attention(q, k, v, layout, BLOCK, causal=causal)
    out_d = dense_blocksparse_attention(q, k, v, layout, BLOCK, causal=causal)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d), rtol=3e-5, atol=3e-5)


def test_kernel_backward_parity():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=4)
    layout = cfg.make_layout(T)
    q, k, v = qkv()
    g = jax.random.normal(jax.random.PRNGKey(5), q.shape)
    gs = jax.grad(lambda q, k, v: jnp.sum(block_sparse_attention(q, k, v, layout, BLOCK) * g),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_blocksparse_attention(q, k, v, layout, BLOCK) * g),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gs, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4,
                                   err_msg=f"d{n}")


def test_dense_config_matches_full_attention():
    from deepspeed_tpu.ops.pallas.flash_attention import dense_attention
    layout = DenseSparsityConfig(num_heads=H, block=BLOCK).make_layout(T)
    q, k, v = qkv()
    out_s = block_sparse_attention(q, k, v, layout, BLOCK)
    out_full = dense_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_full), rtol=3e-5, atol=3e-5)


# ---------------- modules + utils ----------------

def test_sparse_self_attention_module():
    attn = SparseSelfAttention(FixedSparsityConfig(num_heads=H, block=BLOCK))
    q, k, v = qkv()
    out = attn(q, k, v)
    assert out.shape == q.shape
    # with a key padding mask the dense path is used; zero mask = no-op vs sparse path
    out_masked = attn(q, k, v, key_padding_mask=jnp.zeros((B, T)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_masked), rtol=3e-5, atol=3e-5)


def test_bert_sparse_self_attention():
    layer = BertSparseSelfAttention(hidden_size=H * D, num_attention_heads=H,
                                    sparsity_config=FixedSparsityConfig(num_heads=H, block=BLOCK))
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, H * D), jnp.float32)
    out = layer.apply(params, x)
    assert out.shape == (B, T, H * D)


def test_pad_unpad_roundtrip():
    ids = jnp.ones((2, 100), jnp.int32)
    mask = jnp.ones((2, 100), jnp.int32)
    pad_len, ids_p, mask_p, _, _, _ = SparseAttentionUtils.pad_to_block_size(
        block_size=64, input_ids=ids, attention_mask=mask, pad_token_id=9)
    assert pad_len == 28
    assert ids_p.shape == (2, 128)
    assert int(ids_p[0, -1]) == 9 and int(mask_p[0, -1]) == 0
    out = SparseAttentionUtils.unpad_sequence_output(pad_len, ids_p)
    assert out.shape == (2, 100)


def test_extend_position_embedding():
    pe = jnp.arange(16 * 4, dtype=jnp.float32).reshape(16, 4)
    ext = SparseAttentionUtils.extend_position_embedding(pe, 40)
    assert ext.shape == (40, 4)
    np.testing.assert_array_equal(np.asarray(ext[16:32]), np.asarray(pe))


@pytest.mark.parametrize("group", [1, 2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_grouped_kernel_parity(group, causal):
    """Row-group union LUT + membership masks (VERDICT r2 next #2) must be
    numerically identical to the ungrouped kernel and the dense oracle — fwd AND
    grads, causal included."""
    cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK)
    layout = cfg.make_layout(T)
    assert (T // BLOCK) % group == 0
    q, k, v = qkv()
    out_g = block_sparse_attention(q, k, v, layout, BLOCK, causal=causal, group=group)
    out_d = dense_blocksparse_attention(q, k, v, layout, BLOCK, causal=causal)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_d), rtol=3e-5, atol=3e-5)

    g = jax.random.normal(jax.random.PRNGKey(7), q.shape)
    gs = jax.grad(lambda q, k, v: jnp.sum(
        block_sparse_attention(q, k, v, layout, BLOCK, causal=causal, group=group) * g),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(
        dense_blocksparse_attention(q, k, v, layout, BLOCK, causal=causal) * g),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gs, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4,
                                   err_msg=f"d{n} (group={group})")


@pytest.mark.parametrize("group", [1, 2])
def test_empty_row_inside_nonempty_group(group):
    """An all-masked q-row packed into a group whose union is non-empty must yield
    ZERO output and finite grads (the l-clamp guards the 0/0; regression pin for a
    review-flagged NaN scenario that the clamp in fact prevents)."""
    lay = np.ones((H, T // BLOCK, T // BLOCK), np.int64)
    lay[:, 1, :] = 0   # empty q-row inside group {0,1}
    lay[:, :, 2] = 0   # empty k-column inside a group too (dkv side)
    q, k, v = qkv()
    out = block_sparse_attention(q, k, v, lay, BLOCK, group=group)
    ref = dense_blocksparse_attention(q, k, v, lay, BLOCK)
    assert bool(jnp.isfinite(out).all())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-5)
    row1 = np.asarray(out)[:, :, BLOCK:2 * BLOCK, :]
    np.testing.assert_array_equal(row1, np.zeros_like(row1))
    g = jax.grad(lambda q: jnp.sum(block_sparse_attention(q, k, v, lay, BLOCK,
                                                          group=group)))(q)
    assert bool(jnp.isfinite(g).all())


@pytest.mark.parametrize("group", [1, 2])
def test_dma_path_parity(monkeypatch, group):
    """The manual-DMA kernels remain the production path past the VMEM residency
    budget; force them (the resident fast path otherwise shadows them in every
    test) and re-check fwd + grad parity vs the dense oracle."""
    import deepspeed_tpu.ops.pallas.block_sparse_attention as bsa
    monkeypatch.setattr(bsa, "_resident_fits", lambda *a, **k: False)
    cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK)
    layout = cfg.make_layout(T)
    q, k, v = qkv()
    out = block_sparse_attention(q, k, v, layout, BLOCK, group=group)
    ref = dense_blocksparse_attention(q, k, v, layout, BLOCK)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-5)
    g = jax.random.normal(jax.random.PRNGKey(8), q.shape)
    gs = jax.grad(lambda q, k, v: jnp.sum(block_sparse_attention(
        q, k, v, layout, BLOCK, group=group) * g), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_blocksparse_attention(
        q, k, v, layout, BLOCK) * g), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gs, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4,
                                   err_msg=f"d{n} (dma, group={group})")
