"""HBM memory observatory tests (docs/hbm.md).

Four layers, mirroring the subsystem's own structure:

* **utils/hlo.py parsers** — ``entry_buffer_table`` (per-leaf entry layout,
  dtype/shape/bytes, donation via aliases + buffer_donor) and
  ``temp_allocation_estimate`` (def-to-last-use liveness over the ENTRY
  computation) on real compiled programs and hand-written fixtures.
* **Attribution + model** — manifest signature classification, the per-class
  MAX across a program set, the closed-form ZeRO predictor, and the
  reconciliation verdicts — including the seeded-misattribution fixture
  proving reconciliation FAILS when the model is wrong.
* **Registry scale** — the full lint-registry sweep reconciles on every
  entry within the pinned tolerance, and its stable projection is
  byte-compared against the committed golden (the same file
  scripts/lint.sh regenerates and diffs in CI).
* **Engine + forecast** — telemetry.hbm emits Memory/* scalars without
  changing one HLO instruction; the round-5 OOM frontier (PERF.md) is
  re-derived offline; the flight recorder's dump carries OOM forensics.

Regenerate the golden with:
    ds-tpu hbm --golden-out tests/unit/golden/hbm_registry_sweep.json
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import hbm
from deepspeed_tpu.utils.hlo import (entry_buffer_table, instruction_count,
                                     optimized_hlo, temp_allocation_estimate)
from simple_model import SimpleModel, random_dataset, simple_config

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "hbm_registry_sweep.json")
HIDDEN = 16


def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))


# ------------------------------------------------------------ device stats
def test_device_memory_stats_none_on_cpu():
    """The single memory_stats read of the package: a dict where the backend
    reports watermarks, None where it doesn't (the CPU CI contract) — never
    an exception, never a half-empty dict."""
    stats = hbm.device_memory_stats()
    if jax.default_backend() == "cpu":
        assert stats is None
    else:
        assert isinstance(stats, dict) and stats


def test_device_memory_stats_swallows_device_errors():
    class _Boom:
        def memory_stats(self):
            raise RuntimeError("no stats here")

    assert hbm.device_memory_stats(_Boom()) is None


# ------------------------------------------------------------- hlo parsers
@pytest.fixture(scope="module")
def donated_program_text():
    """Optimized HLO of a jit with one donated argument — exercises the
    entry-layout split, per-leaf byte accounting, and donation detection."""
    def step(state, batch):
        return state + jnp.dot(batch, batch.T).sum(), jnp.tanh(batch)

    jitted = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((), jnp.float32)
    batch = jnp.ones((8, 16), jnp.float32)
    return optimized_hlo(jitted, state, batch)


def test_entry_buffer_table_bytes_and_donation(donated_program_text):
    table = entry_buffer_table(donated_program_text)
    params = table["parameters"]
    assert len(params) == 2
    by_bytes = sorted(p["bytes"] for p in params)
    assert by_bytes == [4, 8 * 16 * 4]
    assert table["parameter_bytes"] == 4 + 8 * 16 * 4
    # the donated f32[] scalar aliases an output; the batch does not
    donated = [p for p in params if p["donated"]]
    assert len(donated) == 1 and donated[0]["bytes"] == 4
    assert table["result_bytes"] >= 4 + 8 * 16 * 4
    assert (table["aliased_result_bytes"]
            + table["unaliased_result_bytes"]) == table["result_bytes"]
    assert table["aliased_result_bytes"] >= 4


def test_entry_buffer_table_fixture_layout():
    text = """
HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }, entry_computation_layout={(f32[4,4]{1,0}, bf16[8]{0})->(f32[4,4]{1,0}, bf16[8]{0})}

ENTRY main {
  p0 = f32[4,4]{1,0} parameter(0)
  p1 = bf16[8]{0} parameter(1)
  t = f32[4,4]{1,0} add(p0, p0)
  ROOT out = (f32[4,4]{1,0}, bf16[8]{0}) tuple(t, p1)
}
"""
    table = entry_buffer_table(text)
    assert table["parameter_bytes"] == 4 * 4 * 4 + 8 * 2
    assert [p["donated"] for p in table["parameters"]] == [True, False]
    assert table["aliased_result_bytes"] == 64
    assert table["unaliased_result_bytes"] == 16


def test_temp_allocation_estimate_liveness():
    """Hand-written ENTRY with a known liveness peak: a and b overlap (128 B)
    before c replaces them — parameters and ROOT are excluded."""
    text = """
HloModule m

ENTRY main {
  p0 = f32[4,4]{1,0} parameter(0)
  a = f32[4,4]{1,0} add(p0, p0)
  b = f32[4,4]{1,0} multiply(%a, %a)
  ROOT c = f32[4,4]{1,0} subtract(%a, %b)
}
"""
    assert temp_allocation_estimate(text) == 128


def test_temp_allocation_estimate_on_compiled(donated_program_text):
    est = temp_allocation_estimate(donated_program_text)
    assert isinstance(est, int) and est >= 0


# ------------------------------------------------- classification + model
def test_manifest_signatures_and_classification(donated_program_text):
    """Classifying a program against a manifest whose class matches the batch
    leaf by (dtype, shape): the 512-byte batch lands in the class, the
    scalar falls through to other."""
    manifest = {"classes": {"params": [jnp.ones((8, 16), jnp.float32)]},
                "geometry": {}}
    sigs, class_bytes = hbm.manifest_signatures(manifest)
    assert class_bytes == {"params": 8 * 16 * 4}
    rep = hbm.classify_program(donated_program_text, sigs)
    assert rep["by_class"].get("params") == 8 * 16 * 4
    assert rep["parameter_bytes"] == 4 + 8 * 16 * 4


def test_attribute_programs_takes_per_class_max():
    reports = [{"by_class": {"params": 100, "grads": 10}},
               {"by_class": {"params": 80, "optimizer": 50}}]
    assert hbm.attribute_programs(reports) == {
        "params": 100, "grads": 10, "optimizer": 50}


def test_modeled_classes_zero2_sharding_fraction():
    """ZeRO-2 over dp=8 with 97% coverage: grads/master/optimizer shard to
    frac = 1 - zsf + zsf/dp per device, params stay replicated (stage < 3)."""
    psi, zsf, dp = 1000, 0.97, 8
    geo = {"kind": "training", "psi": psi, "param_itemsize": 4,
           "grad_itemsize": 4, "dp": dp, "zero_stage": 2,
           "zero_sharded_fraction": zsf, "external_master": False,
           "offload": False, "fused": False, "comm_ef_bytes": 0}
    classes = hbm.modeled_classes(geo)
    frac = 1.0 - zsf + zsf / dp
    assert classes["params"] == 4 * psi
    assert classes["grads"] == int(4 * psi * frac)
    assert classes["master"] == int(4 * psi * frac)
    assert classes["optimizer"] == int(8 * psi * frac)
    # stage 1 keeps grads replicated
    geo1 = dict(geo, zero_stage=1)
    assert hbm.modeled_classes(geo1)["grads"] == 4 * psi


def test_reconcile_verdicts():
    classes, ok = hbm.reconcile({"params": 1000, "grads": 0},
                                {"params": 1010, "grads": 500},
                                rel_tol=0.02, abs_tol=16)
    assert ok
    assert classes["params"]["status"] == "ok"
    assert classes["grads"]["status"] == "unobserved"
    classes, ok = hbm.reconcile({"params": 1000}, {"params": 2000},
                                rel_tol=0.02, abs_tol=16)
    assert not ok and classes["params"]["status"] == "drift"


# --------------------------------------------------------- registry sweep
@pytest.fixture(scope="module")
def registry_sweep():
    """The full lint-registry sweep, captured once per module (13 engine
    builds — the same surface scripts/lint.sh gates in CI)."""
    return hbm.sweep_registry()


def test_registry_sweep_reconciles_every_entry(registry_sweep):
    """THE model-accuracy gate: parsed-vs-modeled agree within the pinned
    tolerance on every lint-registry entry, no errors, no drift."""
    assert registry_sweep["errors"] == []
    assert registry_sweep["drift_entries"] == []
    assert registry_sweep["ok"]
    for entry, rep in registry_sweep["entries"].items():
        assert rep["reconciled"], entry
        # every entry attributes SOMETHING: params at minimum
        assert rep["classes"].get("params", {}).get("parsed_bytes", 0) > 0, \
            entry


def test_registry_sweep_matches_golden_bytes(registry_sweep):
    """The stable projection (parsed/modeled bytes + verdicts, no
    XLA-scheduler-dependent watermarks), byte-for-byte against the pinned
    golden scripts/lint.sh regenerates and diffs in CI."""
    text = json.dumps(hbm.stable_projection(registry_sweep), indent=2,
                      sort_keys=True) + "\n"
    with open(GOLDEN) as f:
        golden = f.read()
    assert text == golden, ("hbm sweep drifted from golden (regen via "
                            "ds-tpu hbm --golden-out, see module doc)")


def test_seeded_misattribution_fails_reconciliation(registry_sweep):
    """The negative control: feed the reconciler a WRONG model (psi doubled,
    as if the predictor missed half the parameter tree) and it must flag
    drift — proving the all-ok sweep is a real check, not a tautology."""
    rep = registry_sweep["entries"]["standard"]
    parsed = {c: row["parsed_bytes"] for c, row in rep["classes"].items()}
    wrong_geometry = dict(rep["geometry"])
    wrong_geometry["psi"] = int(wrong_geometry["psi"]) * 2
    wrong_modeled = hbm.modeled_classes(wrong_geometry)
    _, ok = hbm.reconcile(parsed, wrong_modeled)
    assert not ok
    # and the diff gate catches parsed growth the same way
    grown = json.loads(json.dumps(registry_sweep))
    row = grown["entries"]["standard"]["classes"]["params"]
    row["parsed_bytes"] = row["parsed_bytes"] * 10
    diff = hbm.diff_reports(registry_sweep, grown)
    assert not diff["ok"] and any("standard/params" in r
                                  for r in diff["regressions"])


# ----------------------------------------------------------------- forecast
def test_forecast_round5_rederives_oom_frontier():
    """The acceptance headline: every config that OOMed in the round-5 sweep
    (PERF.md) is predicted infeasible, every config that ran is predicted
    feasible, and the winner fits — all offline, no compile, no device."""
    report = hbm.forecast_round5()
    assert report["ok"], report["mismatches"]
    assert report["mismatches"] == []
    cells = {(c["remat"], c["batch"], c["ce_chunk"]): c
             for c in report["cells"]}
    assert len(cells) == len(hbm.ROUND5_SWEEP)
    for remat, batch, chunk, oomed in hbm.ROUND5_SWEEP:
        cell = cells[(remat, batch, chunk)]
        assert cell["predicted_fits"] == (not oomed), cell
    assert cells[hbm.ROUND5_WINNER]["predicted_fits"]


def test_forecast_headroom_and_fitting_deltas():
    cfg = {"model": dict(hbm.ROUND5_MODEL), "remat": "dots+attn",
           "batch_per_device": 8, "seq_len": 1024, "ce_chunk": 128,
           "external_master_shards": hbm.ROUND5_SHARDS, "dp": 1,
           "budget_gib": hbm.ROUND5_BUDGET_GIB}
    f = hbm.forecast(cfg)
    assert not f["fits"] and f["headroom_bytes"] < 0
    deltas = hbm.smallest_fitting_delta(cfg)
    assert deltas, "no single-knob fix found for a near-miss config"
    for d in deltas:
        fixed = json.loads(json.dumps(cfg))
        fixed[d["change"]] = d["value"]
        assert hbm.forecast(fixed)["fits"], d


def test_gpt2_param_count_1p5b():
    assert hbm.gpt2_param_count(**hbm.ROUND5_MODEL) == 1_557_686_400


# ------------------------------------------------------------ engine scale
def test_engine_memory_manifest_classes():
    eng = _build(zero_optimization={"stage": 2})
    manifest = eng.memory_manifest()
    classes = manifest["classes"]
    assert {"params", "grads", "master", "optimizer"} <= set(classes)
    geo = manifest["geometry"]
    assert geo["kind"] == "training" and geo["psi"] > 0
    _, class_bytes = hbm.manifest_signatures(manifest)
    assert all(v > 0 for v in class_bytes.values())


def test_hbm_scalars_ride_end_step(tmp_path):
    eng = _build(telemetry={"enabled": True, "output_path": str(tmp_path),
                            "job_name": "tel", "hbm": {"enabled": True}})
    assert eng.telemetry._memory_class_bytes is not None
    xs, ys = _batch()
    for _ in range(2):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    eng.telemetry.close()
    path = os.path.join(str(tmp_path), "tel", "scalars.jsonl")
    scalars = [json.loads(l) for l in open(path)]
    by_tag = {}
    for s in scalars:
        by_tag.setdefault(s["tag"], []).append(s["value"])
    mem_tags = sorted(t for t in by_tag if t.startswith("Memory/"))
    assert "Memory/params_bytes" in mem_tags
    assert "Memory/compiled_temp_peak_bytes" in mem_tags
    assert all(v > 0 for v in by_tag["Memory/params_bytes"])
    # the scalar is the manifest constant: identical every step
    assert len(set(by_tag["Memory/params_bytes"])) == 1


def test_hbm_keeps_step_path_hlo_identical(tmp_path):
    """THE non-perturbation gate: telemetry.hbm only installs host dicts —
    with it on, every program compiles to instruction-identical HLO."""
    model = SimpleModel(HIDDEN)
    engines = []
    for tel in (None, {"enabled": True, "output_path": str(tmp_path),
                       "hbm": {"enabled": True}}):
        over = dict(zero_optimization={"stage": 2})
        if tel:
            over["telemetry"] = tel
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
            config_params=simple_config(**over))
        engines.append(eng)
    eng_off, eng_on = engines
    batch = _batch()
    progs_off = {n: (j, a) for n, j, a, _m in eng_off.lint_programs(batch)}
    progs_on = {n: (j, a) for n, j, a, _m in eng_on.lint_programs(batch)}
    assert sorted(progs_off) == sorted(progs_on)
    for name in sorted(progs_off):
        h_off = optimized_hlo(*progs_off[name][0:1],
                              *progs_off[name][1])
        h_on = optimized_hlo(*progs_on[name][0:1], *progs_on[name][1])
        assert instruction_count(h_off) > 0, name
        assert instruction_count(h_off) == instruction_count(h_on), name


def test_hbm_requires_telemetry():
    with pytest.raises(ValueError, match="telemetry.hbm.enabled requires"):
        _build(telemetry={"hbm": {"enabled": True}})


# ------------------------------------------------------------ OOM forensics
def test_memory_snapshot_and_oom_forensics():
    from deepspeed_tpu.utils.monitor import SummaryMonitor
    from deepspeed_tpu.utils.telemetry import TelemetrySession
    session = TelemetrySession(monitor=SummaryMonitor(enabled=False))
    assert session.memory_snapshot() is None
    cfg = {"model": dict(hbm.ROUND5_MODEL), "remat": "dots+attn",
           "batch_per_device": 8, "seq_len": 1024, "ce_chunk": 128,
           "external_master_shards": hbm.ROUND5_SHARDS, "dp": 1,
           "budget_gib": hbm.ROUND5_BUDGET_GIB}
    session.set_memory_manifest({"params": 400, "optimizer": 1200},
                                geometry={"kind": "training"},
                                forecast_config=cfg)
    snap = session.memory_snapshot()
    assert snap["classes"] == {"params": 400, "optimizer": 1200}
    forensics = hbm.oom_forensics(snap)
    assert [r["class"] for r in forensics["largest_classes"]] == [
        "optimizer", "params"]
    # the registered config OOMs, so forensics names the smallest fixes
    assert forensics["forecast"]["fits"] is False
    assert forensics["fitting_deltas"]
    session.close()


def test_flight_recorder_dump_carries_hbm_block(tmp_path):
    eng = _build(telemetry={"enabled": True, "output_path": str(tmp_path),
                            "hbm": {"enabled": True}},
                 numerics={"enabled": True,
                           "dump_dir": str(tmp_path / "dumps")})
    xs, ys = _batch()
    loss = eng(xs, ys)
    eng.backward(loss)
    eng.step()
    bundle = eng._numerics.recorder.bundle("test")
    assert "hbm" in bundle
    assert bundle["hbm"]["classes"].get("params", 0) > 0
    assert bundle["hbm"]["largest_classes"]
    eng.telemetry.close()


# ------------------------------------------------- mem_unavailable satellite
def test_compile_mem_unavailable_warns_once_per_backend(tmp_path,
                                                        monkeypatch):
    """The fixed silent-except: when compiled.memory_analysis raises, the
    compile record carries mem_unavailable=True and ONE warning names the
    backend — not a silent pass, not a warning storm."""
    import logging

    from deepspeed_tpu.utils import telemetry as tel_mod
    from deepspeed_tpu.utils.logging import logger

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    monkeypatch.setattr(tel_mod, "_mem_unavailable_warned", set())
    real = tel_mod._analyze_compiled

    class _NoMem:
        def __init__(self, compiled):
            self._c = compiled

        def cost_analysis(self):
            return self._c.cost_analysis()

        def memory_analysis(self):
            raise RuntimeError("synthetic backend without memory_analysis")

        def as_text(self):
            return self._c.as_text()

    monkeypatch.setattr(
        tel_mod, "_analyze_compiled",
        lambda compiled, *a, **kw: real(_NoMem(compiled), *a, **kw))
    handler = _Capture()
    logger.addHandler(handler)
    try:
        eng = _build(telemetry={"enabled": True,
                                "output_path": str(tmp_path)})
        xs, ys = _batch()
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    finally:
        logger.removeHandler(handler)
    recs = [r for prog in eng.telemetry.watchdog.records.values()
            for r in prog.values()]
    assert recs and all(r.mem_unavailable for r in recs)
    assert all(r.argument_bytes == 0 and r.temp_bytes == 0 for r in recs)
    warned = [m for m in records if "memory_analysis is unavailable" in m]
    assert len(warned) == 1 and "'cpu'" in warned[0]
    eng.telemetry.close()


def test_compile_mem_available_on_cpu(tmp_path):
    """The flip side: jax's CPU backend DOES report memory_analysis, so the
    default path records real byte counts with mem_unavailable False."""
    eng = _build(telemetry={"enabled": True, "output_path": str(tmp_path)})
    xs, ys = _batch()
    loss = eng(xs, ys)
    eng.backward(loss)
    eng.step()
    recs = [r for prog in eng.telemetry.watchdog.records.values()
            for r in prog.values()]
    assert recs and all(not r.mem_unavailable for r in recs)
    assert any(r.argument_bytes > 0 for r in recs)
    eng.telemetry.close()
