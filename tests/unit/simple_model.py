"""Tiny model fixtures (analog of reference tests/unit/simple_model.py)."""

import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel:
    """Two-layer MLP returning MSE loss: model(params, x, y) -> loss."""

    def __init__(self, hidden_dim=16):
        self.hidden_dim = hidden_dim

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        h = self.hidden_dim
        return {
            "w1": jax.random.normal(k1, (h, h), jnp.float32) * 0.1,
            "b1": jnp.zeros((h,), jnp.float32),
            "w2": jax.random.normal(k2, (h, h), jnp.float32) * 0.1,
            "b2": jnp.zeros((h,), jnp.float32),
        }

    def apply(self, params, x, y):
        h = jnp.tanh(x @ params["w1"].astype(x.dtype) + params["b1"].astype(x.dtype))
        out = h @ params["w2"].astype(x.dtype) + params["b2"].astype(x.dtype)
        return jnp.mean(jnp.square(out - y).astype(jnp.float32))


def random_dataset(total_samples, hidden_dim, seed=0, dtype=np.float32):
    """Inputs are gaussian; targets are a fixed linear map of the inputs (learnable)."""
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(total_samples, hidden_dim)).astype(dtype)
    w_true = np.random.default_rng(1234).normal(size=(hidden_dim, hidden_dim)).astype(dtype) * 0.3
    ys = np.tanh(xs @ w_true)
    return [(xs[i], ys[i]) for i in range(total_samples)]


def simple_config(batch=8, **overrides):
    cfg = {
        "train_batch_size": batch,
        "steps_per_print": 100,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(overrides)
    return cfg
