"""Partitioning-utility tests (analog of reference ``tests/unit/test_partition.py``:
partition_balanced l.14+ and PartitionedTensor l.100+)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.utils import (PartitionedTensor, partition_balanced,
                                         partition_uniform)


def _part_weights(weights, parts):
    return [sum(weights[parts[p]:parts[p + 1]]) for p in range(len(parts) - 1)]


def test_partition_uniform():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(10, 3) == [0, 3, 6, 10]
    # fewer items than parts: one item per leading part (reference semantics)
    assert partition_uniform(2, 4) == [0, 1, 2, 2, 2]


def test_partition_balanced_uniform_weights():
    parts = partition_balanced([1.0] * 8, 4)
    assert parts == [0, 2, 4, 6, 8]


def test_partition_balanced_skewed():
    weights = [1, 1, 1, 1, 10]
    parts = partition_balanced(weights, 2)
    # the heavy item must sit alone-ish: bottleneck is 10, everything else in part 0
    assert parts[0] == 0 and parts[-1] == 5
    loads = _part_weights(weights, parts)
    assert max(loads) == 10, (parts, loads)


def test_partition_balanced_monotone_and_complete():
    rng = np.random.default_rng(0)
    weights = rng.integers(1, 50, 23).tolist()
    for parts_n in (2, 3, 5, 7):
        parts = partition_balanced(weights, parts_n)
        assert len(parts) == parts_n + 1
        assert parts[0] == 0 and parts[-1] == len(weights)
        assert all(b >= a for a, b in zip(parts, parts[1:])), parts
        # bottleneck optimality sanity: no single item exceeds the max load
        loads = _part_weights(weights, parts)
        assert max(loads) >= max(weights) - 1e-9


@pytest.mark.parametrize("shape", [(7,), (3, 5), (4, 4, 2)])
@pytest.mark.parametrize("world", [1, 2, 4])
def test_partitioned_tensor_round_trip(shape, world):
    x = jnp.arange(int(np.prod(shape)), dtype=jnp.float32).reshape(shape)
    parts = [PartitionedTensor(x, world, r) for r in range(world)]
    # equal chunks, padded
    sizes = {int(p.local_data.size) for p in parts}
    assert len(sizes) == 1
    full = parts[0].full([p.local_data for p in parts])
    np.testing.assert_array_equal(np.asarray(full), np.asarray(x))


def test_partitioned_tensor_meta_round_trip():
    x = jnp.arange(10, dtype=jnp.bfloat16).reshape(2, 5)
    world = 4
    parts = [PartitionedTensor(x, world, r) for r in range(world)]
    meta = parts[0].to_meta()
    # reconstruct rank-2's view purely from (meta, local_data) — the cross-process path
    rebuilt = PartitionedTensor.from_meta(meta, parts[2].local_data, world, 2)
    assert rebuilt.orig_shape == (2, 5)
    full = rebuilt.full([p.local_data for p in parts])
    assert full.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(full, np.float32), np.asarray(x, np.float32))
