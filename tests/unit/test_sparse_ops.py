"""Parity tests for the standalone block-sparse MatMul / Softmax ops.

Mirrors the reference's ``tests/unit/test_sparse_attention.py`` kernel checks
(``test_matmul`` sweeping sdd/dsd/dds × trans_a/trans_b l.334+, ``test_softmax`` l.252):
every sparse op is compared against the dense torch-equivalent computation restricted to
the layout's active blocks — here against dense jnp with inactive blocks masked.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (FixedSparsityConfig, MatMul, Softmax,
                                                dense_to_sparse, sparse_to_dense)

B, H, T, BLOCK = 2, 4, 64, 16


def make_layout(seed=0):
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=2,
                              num_global_blocks=1, attention="bidirectional",
                              different_layout_per_head=True, num_different_global_patterns=2)
    layout = cfg.make_layout(T)
    assert layout.sum() < layout.size, "layout should actually be sparse"
    return layout


def dense_mask(layout):
    """[H, T, T] 0/1 mask expanded from the block layout."""
    return np.kron(np.asarray(layout), np.ones((BLOCK, BLOCK))).astype(np.float32)


@pytest.mark.parametrize("trans_a", [False, True])
@pytest.mark.parametrize("trans_b", [False, True])
def test_matmul_sdd(trans_a, trans_b):
    layout = make_layout()
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(size=(B, H, T, T)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(B, H, T, T)).astype(np.float32))
    op = MatMul(layout, BLOCK, "sdd", trans_a=trans_a, trans_b=trans_b)
    vals = op(a, b)
    a_eff = a.swapaxes(-1, -2) if trans_a else a
    b_eff = b.swapaxes(-1, -2) if trans_b else b
    want = np.asarray(a_eff @ b_eff) * dense_mask(layout)
    got = np.asarray(sparse_to_dense(vals, layout, BLOCK))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode", ["dsd", "dds"])
@pytest.mark.parametrize("trans_sparse", [False, True])
def test_matmul_sparse_operand(mode, trans_sparse):
    layout = make_layout()
    rng = np.random.default_rng(1)
    sp_dense = jnp.asarray((rng.normal(size=(B, H, T, T)) * dense_mask(layout)).astype(np.float32))
    vals = dense_to_sparse(sp_dense, layout, BLOCK)
    dn = jnp.asarray(rng.normal(size=(B, H, T, T)).astype(np.float32))
    sp_eff = np.asarray(sp_dense).swapaxes(-1, -2) if trans_sparse else np.asarray(sp_dense)
    if mode == "dsd":
        op = MatMul(layout, BLOCK, "dsd", trans_a=trans_sparse)
        got = np.asarray(op(vals, dn))
        want = sp_eff @ np.asarray(dn)
    else:
        op = MatMul(layout, BLOCK, "dds", trans_b=trans_sparse)
        got = np.asarray(op(dn, vals))
        want = np.asarray(dn) @ sp_eff
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_matmul_grads_flow():
    layout = make_layout()
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.normal(size=(B, H, T, T)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(B, H, T, T)).astype(np.float32))
    sdd = MatMul(layout, BLOCK, "sdd")
    dsd = MatMul(layout, BLOCK, "dsd")

    def f(a, b):
        return jnp.sum(dsd(sdd(a, b), b) ** 2)

    ga, gb = jax.grad(f, argnums=(0, 1))(a, b)

    mask = jnp.asarray(dense_mask(layout))

    def f_dense(a, b):
        return jnp.sum((((a @ b) * mask) @ b) ** 2)

    ga_d, gb_d = jax.grad(f_dense, argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(ga_d), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_d), rtol=1e-2, atol=1e-2)


def _dense_reference_softmax(scores, layout, scale, rpe=None, kp=None, am=None,
                             kp_mode="add", am_mode="mul"):
    """Dense masked softmax restricted to layout-active positions."""
    mask = dense_mask(layout)[None]                      # [1, H, T, T]
    x = np.asarray(scores, np.float64) * scale
    if rpe is not None:
        rpe = np.asarray(rpe, np.float64)
        x = x + (rpe if rpe.ndim == 4 else rpe[None])
    if am is not None:
        # "mul" reference-kernel semantics: zero -> -inf, nonzero -> score UNCHANGED
        am = np.asarray(am, np.float64)[None, None]
        x = np.where(am == 0, -np.inf, x) if am_mode == "mul" else x + am
    if kp is not None:
        kp = np.asarray(kp, np.float64)[:, None, None, :]
        x = np.where(kp == 0, -np.inf, x) if kp_mode == "mul" else x + kp
    x = np.where(mask == 0, -np.inf, x)
    m = np.max(x, -1, keepdims=True)
    e = np.exp(x - np.where(np.isfinite(m), m, 0.0))
    e = np.where(np.isfinite(x), e, 0.0)
    s = e.sum(-1, keepdims=True)
    return np.where(s > 0, e / np.where(s > 0, s, 1.0), 0.0)


def test_softmax_parity():
    layout = make_layout()
    rng = np.random.default_rng(3)
    scores = rng.normal(size=(B, H, T, T)).astype(np.float32)
    vals = dense_to_sparse(jnp.asarray(scores), layout, BLOCK)
    sm = Softmax(layout, BLOCK)
    got = np.asarray(sparse_to_dense(sm(vals, scale=0.5), layout, BLOCK))
    want = _dense_reference_softmax(scores, layout, 0.5) * dense_mask(layout)[None]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_softmax_masks_and_rpe():
    layout = make_layout()
    rng = np.random.default_rng(4)
    scores = rng.normal(size=(B, H, T, T)).astype(np.float32)
    vals = dense_to_sparse(jnp.asarray(scores), layout, BLOCK)
    rpe = rng.normal(size=(H, T, T)).astype(np.float32)
    kp = np.zeros((B, T), np.float32)
    kp[:, T // 2:] = -10000.0                    # "add" mode: large negative on padding
    # non-binary "mul" mask: nonzero values must leave scores UNCHANGED (not scale them)
    am = np.tril(np.ones((T, T), np.float32)) * 3.0
    sm = Softmax(layout, BLOCK)
    got = np.asarray(sparse_to_dense(
        sm(vals, scale=1.0, rpe=rpe, key_padding_mask=kp, attn_mask=am,
           key_padding_mask_mode="add", attn_mask_mode="mul"), layout, BLOCK))
    want = _dense_reference_softmax(scores, layout, 1.0, rpe=rpe, kp=kp, am=am,
                                    kp_mode="add", am_mode="mul") * dense_mask(layout)[None]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_softmax_batched_rpe():
    """Per-batch [B, H, T, T] rpe (reference kernel strides rpe by batch:
    softmax_fwd.tr pidz * stride_zrpe); [B, 1, T, T] broadcasts over heads."""
    layout = make_layout()
    rng = np.random.default_rng(5)
    scores = rng.normal(size=(B, H, T, T)).astype(np.float32)
    vals = dense_to_sparse(jnp.asarray(scores), layout, BLOCK)
    sm = Softmax(layout, BLOCK)
    for rpe_shape in [(B, H, T, T), (B, 1, T, T)]:
        rpe = rng.normal(size=rpe_shape).astype(np.float32)
        got = np.asarray(sparse_to_dense(sm(vals, scale=0.5, rpe=rpe), layout, BLOCK))
        want = _dense_reference_softmax(
            scores, layout, 0.5,
            rpe=np.broadcast_to(rpe, (B, H, T, T))) * dense_mask(layout)[None]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sdd_softmax_dsd_pipeline_matches_dense_attention():
    """The reference's SparseSelfAttention pipeline (sparse_self_attention.py:83-142):
    sdd(q, k^T) -> scaled sparse softmax -> dsd(probs, v) == dense masked attention."""
    layout = make_layout()
    rng = np.random.default_rng(5)
    D = 32
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    scale = 1.0 / np.sqrt(D)

    sdd = MatMul(layout, BLOCK, "sdd", trans_b=True)
    sm = Softmax(layout, BLOCK)
    dsd = MatMul(layout, BLOCK, "dsd")
    got = np.asarray(dsd(sm(sdd(q, k), scale=scale), v))

    mask = dense_mask(layout)[None]
    scores = np.asarray(q @ k.swapaxes(-1, -2)) * scale
    scores = np.where(mask == 0, -np.inf, scores)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs = np.where(np.isfinite(scores), probs, 0.0)
    probs = probs / probs.sum(-1, keepdims=True)
    want = probs @ np.asarray(v)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
