"""Topology/grid rank-math tests (parity with reference tests/unit/test_topology.py)."""

import pytest

from deepspeed_tpu.parallel.topology import (ProcessTopology as Topo, PipelineParallelGrid as Grid,
                                             PipeDataParallelTopology, PipeModelDataParallelTopology,
                                             _prime_factors)


def test_topology_2d():
    topo = Topo(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3
    assert topo.get_axis_list(axis="row", idx=0) == [0, 1]
    assert topo.get_axis_list(axis="row", idx=1) == [2, 3]
    assert topo.get_axis_list(axis="col", idx=0) == [0, 2]
    assert topo.get_axis_list(axis="col", idx=1) == [1, 3]


def test_topology_dims():
    topo = Topo(axes=["a", "b", "c"], dims=[2, 3, 4])
    assert topo.world_size() == 24
    assert topo.get_dim("a") == 2
    assert topo.get_dim("b") == 3
    assert topo.get_dim("c") == 4


def test_topology_match():
    topo = Topo(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.filter_match(pipe=0, data=1) == [2, 3]


def test_topology_rank_repr():
    topo = Topo(axes=["a", "b"], dims=[2, 2])
    assert topo.get_rank_repr(rank=0) == "a_00-b_00"
    assert topo.get_rank_repr(rank=1) == "a_00-b_01"
    assert topo.get_rank_repr(rank=2) == "a_01-b_00"
    assert topo.get_rank_repr(rank=3) == "a_01-b_01"
    assert topo.get_rank_repr(rank=3, inner_sep="+") == "a+01-b+01"

    topo = Topo(axes=["pipe", "data"], dims=[2, 2])
    for r in range(4):
        assert topo.get_rank_repr(rank=r) == ""
    assert topo.get_rank_repr(rank=0, omit_axes=["pipe"]) == "data_00"
    assert topo.get_rank_repr(rank=0, omit_axes=[]) == "pipe_00-data_00"
    assert topo.get_rank_repr(rank=3, omit_axes=[]) == "pipe_01-data_01"

    topo = Topo(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.get_rank_repr(rank=0) == "model_00"
    assert topo.get_rank_repr(rank=1) == "model_01"
    assert topo.get_rank_repr(rank=7) == "model_01"


def test_topology_3d():
    topo = Topo(axes=["a", "b", "c"], dims=[2, 2, 2])
    assert topo.get_rank(a=0, b=0, c=0) == 0
    assert topo.get_rank(a=0, b=1, c=1) == 3
    assert topo.get_rank(a=1, b=1, c=1) == 7
    assert topo.get_axis_list("a", 0) == [0, 1, 2, 3]
    assert topo.get_axis_list("b", 1) == [2, 3, 6, 7]
    assert topo.get_axis_list("c", 0) == [0, 2, 4, 6]
    assert topo.get_coord(3) == topo.ProcessCoord(0, 1, 1)
    assert topo.filter_match(a=0) == [0, 1, 2, 3]
    assert topo.filter_match(b=1, c=1) == [3, 7]
    assert topo.get_coord(0).a == 0


def test_topology_comm_list():
    topo = Topo(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.get_axis_comm_lists("pipe") == [[0, 4], [1, 5], [2, 6], [3, 7]]
    assert topo.get_axis_comm_lists("data") == [[0, 2], [1, 3], [4, 6], [5, 7]]
    assert topo.get_axis_comm_lists("model") == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert topo.get_axis_comm_lists("jeff") == []


def test_grid_pipe_data():
    topo = Topo(axes=["pipe", "data"], dims=[2, 2])
    for rank in range(4):
        grid = Grid(topology=topo, global_rank=rank)
        assert grid._is_grid_valid()
        assert grid.is_first_stage == (grid.get_stage_id() == 0)
        assert grid.is_last_stage == (grid.get_stage_id() == grid.get_pipe_parallel_world_size() - 1)
        assert rank in grid.pp_group
        assert rank in grid.dp_group


def test_stage_to_global():
    topo = Topo(axes=["pipe", "data"], dims=[2, 2])
    grid = Grid(topology=topo, global_rank=0)
    assert grid.stage_to_global(stage_id=0, data=0) == 0
    assert grid.stage_to_global(stage_id=0, data=1) == 1
    assert grid.stage_to_global(stage_id=1, data=0) == 2
    assert grid.stage_to_global(stage_id=1, data=1) == 3
    assert grid.stage_to_global(stage_id=0) == 0
    assert grid.stage_to_global(stage_id=1) == 2
    grid1 = Grid(topology=topo, global_rank=1)
    assert grid1.stage_to_global(stage_id=0) == 1
    assert grid1.stage_to_global(stage_id=1) == 3


def test_grid_p2p():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = Grid(topology=topo, global_rank=0)
    # p2p buddy of rank r is the next stage with same data coord
    assert grid.p2p_groups[0] == [0, 2]
    # wraparound for last stage
    assert grid.p2p_groups[6] == [6, 0]


def test_3d_grid_mpu_interface():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    grid = Grid(topology=topo, global_rank=5)
    assert grid.get_pipe_parallel_world_size() == 2
    assert grid.get_data_parallel_world_size() == 2
    assert grid.get_slice_parallel_world_size() == 2
    coord = topo.get_coord(5)
    assert grid.get_pipe_parallel_rank() == coord.pipe
    assert grid.get_data_parallel_rank() == coord.data
    assert grid.get_slice_parallel_rank() == coord.model


def test_primes():
    def _product(ps):
        p = 1
        for x in ps:
            p *= x
        return p

    for n in [2, 3, 4, 10, 12, 36, 97]:
        ps = _prime_factors(n)
        assert _product(ps) == n
        assert ps == sorted(ps)
    with pytest.raises(ValueError):
        _prime_factors(0)
