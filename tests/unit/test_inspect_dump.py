"""CLI smoke test: forced-NaN toy run -> flight-recorder dump -> inspector.

The acceptance path for the whole observatory: a training run that goes bad
must leave a post-mortem bundle that ``ds-tpu inspect-dump`` resolves to the
first bad step and the offending parameter subtree — with no access to the
dead process.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.utils.numerics import inspect_dump_main, summarize_dump
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16
REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _forced_nan_dump(tmp_path):
    """Run a tiny fp16 job, poison w2's grads for two consecutive steps, and
    return the dump the consecutive-skip trigger wrote."""
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(
            fp16={"enabled": True, "initial_scale_power": 4},
            numerics={"enabled": True, "consecutive_skip_trigger": 2,
                      "dump_dir": str(tmp_path)}))
    data = random_dataset(8, HIDDEN, seed=0)
    xs = np.stack([d[0] for d in data])
    ys = np.stack([d[1] for d in data])
    for step in range(3):
        loss = eng(xs, ys)
        eng.backward(loss)
        if step >= 1:  # step 0 healthy, then two poisoned steps in a row
            g = dict(eng._grad_acc)
            g["w2"] = jax.device_put(
                jnp.full(g["w2"].shape, jnp.nan, g["w2"].dtype), g["w2"].sharding)
            eng._grad_acc = g
        eng.step()
    rec = eng._numerics.recorder
    assert rec.dump_count == 1, "consecutive-skip trigger did not fire"
    return rec.last_dump_path


def test_forced_nan_run_dump_resolves(tmp_path, capsys):
    path = _forced_nan_dump(tmp_path)
    bundle = json.load(open(path))
    assert bundle["reason"] == "consecutive_overflow_skips"
    s = summarize_dump(bundle)
    assert s["first_bad_step"] == 2          # first poisoned global step
    assert s["offending_subtree"] == "w2"
    assert s["loss_scale_trajectory"], "journal trajectory missing from bundle"

    # in-process inspector: human-readable output names the step and subtree
    assert inspect_dump_main([path]) == 0
    out = capsys.readouterr().out
    assert "first bad step    : 2" in out
    assert "offending subtree : w2" in out
    assert "loss-scale trajectory" in out

    # --json mode round-trips the summary
    assert inspect_dump_main([path, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["offending_subtree"] == "w2"


def test_ds_tpu_inspect_dump_subprocess(tmp_path):
    """The shipped CLI entry point resolves the dump end to end."""
    path = _forced_nan_dump(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds-tpu"), "inspect-dump", path],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "first bad step    : 2" in proc.stdout
    assert "offending subtree : w2" in proc.stdout
