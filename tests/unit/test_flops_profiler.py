"""FLOPs profiler: XLA cost analysis of compiled programs (utils/flops_profiler.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.flops_profiler import format_report, mfu, profile

from simple_model import SimpleModel, simple_config

H, B = 64, 8


def test_profile_matmul_flops_exact():
    def f(a, b):
        return a @ b

    a = jnp.zeros((128, 256), jnp.float32)
    b = jnp.zeros((256, 512), jnp.float32)
    rpt = profile(f, a, b, peak_tflops=100.0)
    want = 2 * 128 * 256 * 512
    assert abs(rpt["flops"] - want) / want < 0.05, (rpt["flops"], want)
    assert rpt["bytes_accessed"] > 0 and rpt["arithmetic_intensity"] > 0
    assert rpt["optimal_seconds"] > 0
    txt = format_report(rpt, title="matmul")
    assert "matmul" in txt and "flops" in txt
    assert abs(mfu(rpt, rpt["optimal_seconds"], 100.0) - 1.0) < 1e-6


def test_profile_accepts_shape_structs():
    """No data needed: profiling works from ShapeDtypeStructs alone."""
    def f(a):
        return jnp.sum(a * 2.0)

    rpt = profile(f, jax.ShapeDtypeStruct((1024, 1024), jnp.float32))
    assert rpt["flops"] > 0


def _engine(**cfg):
    model = SimpleModel(H)
    return DeepSpeedEngine(model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
                           config_params=simple_config(batch=B, **cfg))


def test_engine_flops_profile_two_jit():
    eng = _engine(zero_optimization={"stage": 2}, bf16={"enabled": True})
    x = np.zeros((B, H), np.float32)
    rpt = eng.flops_profile(x, x)
    assert rpt["programs"] == ["loss_and_grad", "apply_update"]
    assert rpt["params"] == 2 * (H * H + H)
    # SPMD: per-DEVICE numbers — batch 8 shards over the 8-device mesh, so the
    # per-device fwd is 2 matmuls of 2*(B/8)*H*H flops; bwd roughly
    # doubles-to-triples it; the update adds O(P). Bound loosely but meaningfully:
    fwd = 2 * 2 * (B // 8) * H * H
    assert 2 * fwd < rpt["flops"] < 50 * fwd, (rpt["flops"], fwd)
    assert rpt["temp_bytes"] >= 0 and rpt["bytes_accessed"] > 0


def test_engine_flops_profile_onebit_stacked_grads():
    """1-bit Adam keeps per-worker grads stacked with a leading dp axis — the
    profiler's gradient shape structs must carry it (regression: review r4)."""
    eng = _engine(optimizer={"type": "OneBitAdam",
                             "params": {"lr": 1e-3, "freeze_step": 4}})
    x = np.zeros((B, H), np.float32)
    rpt = eng.flops_profile(x, x)
    assert rpt["flops"] > 0 and "apply_update" in rpt["programs"]


def test_engine_flops_profile_fused():
    eng = _engine(fused_step=True, bf16={"enabled": True})
    assert eng._jit_fused is not None
    x = np.zeros((B, H), np.float32)
    rpt = eng.flops_profile(x, x, peak_tflops=197.0)
    assert rpt["programs"] == ["fused_step"]
    assert rpt["flops"] > 0 and rpt["optimal_seconds"] > 0
