"""Block allocator unit tests: alloc/free/fragmentation, refcounted fork +
copy-on-write, and OOM surfacing as AllocationError (admission refusal),
never a crash."""

import pytest

from deepspeed_tpu.serve.block_allocator import (AllocationError,
                                                 BlockAllocator, NULL_BLOCK)


def test_block_zero_is_reserved():
    a = BlockAllocator(8, 4)
    got = a.allocate(7)
    assert NULL_BLOCK not in got
    assert sorted(got) == list(range(1, 8))
    assert a.num_free == 0


def test_ceil_div_blocks_for_tokens():
    a = BlockAllocator(8, 4)
    assert a.blocks_for_tokens(1) == 1
    assert a.blocks_for_tokens(4) == 1
    assert a.blocks_for_tokens(5) == 2
    assert a.blocks_for_tokens(16) == 4


def test_oom_is_a_refusal_not_a_crash():
    a = BlockAllocator(4, 4)          # 3 usable
    a.allocate(2)
    assert not a.can_allocate(2)
    with pytest.raises(AllocationError):
        a.allocate(2)
    assert a.num_free == 1            # failed allocation took nothing


def test_free_returns_blocks_and_double_free_raises():
    a = BlockAllocator(8, 4)
    got = a.allocate(3)
    a.free(got)
    assert a.num_free == 7
    with pytest.raises(ValueError):
        a.free(got)


def test_fragmented_free_list_still_serves_fifo_deterministically():
    """Interleaved alloc/free leaves a shuffled free list; allocation order
    must still be a pure function of the history (replay determinism)."""
    def history(a):
        x = a.allocate(3)
        y = a.allocate(2)
        a.free([x[1]])
        a.free(y)
        a.free([x[0]])
        return a.allocate(4)

    first = history(BlockAllocator(8, 4))
    second = history(BlockAllocator(8, 4))
    assert first == second
    assert len(set(first)) == 4


def test_fork_shares_and_free_releases_at_last_ref():
    a = BlockAllocator(8, 4)
    table = a.allocate(2)
    forked = a.fork(table)
    assert forked == table
    assert all(a.refcount(b) == 2 for b in table)
    a.free(table)
    assert a.num_free == 5            # still held by the fork
    a.free(forked)
    assert a.num_free == 7


def test_ensure_exclusive_copy_on_write():
    a = BlockAllocator(8, 4)
    table = a.allocate(1)
    a.fork(table)
    blk, copy = a.ensure_exclusive(table[0])
    assert blk != table[0]
    assert copy == (table[0], blk)    # device must mirror src -> dst
    assert a.refcount(table[0]) == 1 and a.refcount(blk) == 1
    # already-exclusive page: no copy
    blk2, copy2 = a.ensure_exclusive(blk)
    assert blk2 == blk and copy2 is None


def test_null_block_is_ignored_by_free_and_fork():
    a = BlockAllocator(8, 4)
    a.free([NULL_BLOCK])              # no-op, no raise
    assert a.fork([NULL_BLOCK]) == [NULL_BLOCK]


def test_constructor_validation():
    with pytest.raises(ValueError):
        BlockAllocator(1, 4)          # no room for the null page
    with pytest.raises(ValueError):
        BlockAllocator(8, 0)
