"""Per-group optimizer hyperparameters (reference engine.py:503-650 torch param_groups,
fp16/fused_optimizer.py:48-66): pattern-partitioned leaves with per-group lr/weight_decay,
trajectory parity vs a hand-computed fp64 oracle, scheduler updates every group."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16

# the BERT recipe shape: biases excluded from weight decay, with their own lr
GROUPS = [{"pattern": "^b", "weight_decay": 0.0, "lr": 5e-3}]
BASE_LR, BASE_WD = 1e-2, 0.01


def _two_group_config(**over):
    cfg = simple_config(batch=8)
    cfg["optimizer"] = {"type": "AdamW",
                        "params": {"lr": BASE_LR, "weight_decay": BASE_WD,
                                   "param_groups": GROUPS}}
    cfg.update(over)
    return cfg


def _oracle_adamw(p, g, m, v, step, lr, wd, b1=0.9, b2=0.999, eps=1e-8):
    p, g, m, v = (np.asarray(a, np.float64) for a in (p, g, m, v))
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    update = (m / (1 - b1 ** step)) / (np.sqrt(v / (1 - b2 ** step)) + eps)
    p = p - lr * update - lr * wd * p
    return p, m, v


def _leaf_hypers():
    # SimpleModel leaves: b1/b2 match "^b" -> group 1; w1/w2 -> base group 0
    return {"w1": (BASE_LR, BASE_WD), "w2": (BASE_LR, BASE_WD),
            "b1": (5e-3, 0.0), "b2": (5e-3, 0.0)}


def _run_oracle(params, grad_seq):
    """Apply the engine's OWN gradient sequence with per-group fp64 AdamW: isolates
    the group-routing/update math from fp32 trajectory drift."""
    ref = {k: np.asarray(v, np.float64) for k, v in params.items()}
    m = {k: np.zeros_like(v) for k, v in ref.items()}
    v = {k: np.zeros_like(vv) for k, vv in ref.items()}
    hypers = _leaf_hypers()
    for step, g in enumerate(grad_seq, start=1):
        for k in ref:
            lr, wd = hypers[k]
            ref[k], m[k], v[k] = _oracle_adamw(ref[k], g[k], m[k], v[k], step, lr, wd)
    return ref


def _batches(n, seed=0):
    data = random_dataset(8 * n, HIDDEN, seed=seed)
    return [(np.stack([data[i * 8 + j][0] for j in range(8)]),
             np.stack([data[i * 8 + j][1] for j in range(8)])) for i in range(n)]


@pytest.mark.parametrize("offload", [False, True])
def test_two_group_trajectory_matches_oracle(offload):
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    params0 = jax.device_get(params)  # engine donates the master aliasing these arrays
    cfg = _two_group_config()
    if offload:
        cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    assert len(engine.optimizer.param_groups) == 2
    assert engine.optimizer.param_groups[1]["weight_decay"] == 0.0
    gids = dict(zip(sorted(params), [None] * 4))
    gid_tree = engine._group_index
    assert gid_tree is not None
    gids = {k: gid_tree[k] for k in params}
    assert gids == {"w1": 0, "w2": 0, "b1": 1, "b2": 1}

    grad_seq = []
    for x, y in _batches(4):
        loss = engine(x, y)
        grad_seq.append({k: np.asarray(v, np.float64) for k, v in
                         jax.device_get(engine._pending_grads).items()})
        engine.backward(loss)
        engine.step()

    got = {k: np.asarray(v, np.float64)
           for k, v in jax.device_get(engine.master_params).items()}
    want = _run_oracle(params0, grad_seq)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=3e-5, atol=3e-6,
                                   err_msg=f"leaf {k} diverged from the 2-group oracle")


def test_single_group_unchanged_with_groups_code():
    """No param_groups spec -> exactly the historical single-group behavior."""
    cfg = simple_config()
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    assert engine._group_index is None
    assert len(engine.optimizer.param_groups) == 1
    h = engine.optimizer.current_hyper()
    assert h["lr"].ndim == 0  # scalar jit signature preserved


def test_scheduler_updates_every_group():
    cfg = _two_group_config(scheduler={"type": "WarmupLR",
                                       "params": {"warmup_min_lr": 0.0,
                                                  "warmup_max_lr": [1e-2, 5e-3],
                                                  "warmup_num_steps": 10}})
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    for x, y in _batches(5):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    lrs = engine.get_lr()
    assert len(lrs) == 2
    # WarmupLR is log-warmup: gamma = log(step+1)/log(warmup_num_steps)
    import math
    gamma = math.log(5) / math.log(10)
    np.testing.assert_allclose(lrs, [1e-2 * gamma, 5e-3 * gamma], rtol=1e-6)
    # the device-side hyper really carries both groups
    h = engine.optimizer.current_hyper()
    assert h["lr"].shape == (2,)
    np.testing.assert_allclose(np.asarray(h["lr"]), lrs, rtol=1e-6)


def test_model_hook_param_group_patterns():
    """A model can declare its groups via param_group_patterns() (config absent)."""
    model = SimpleModel(HIDDEN)
    model.param_group_patterns = lambda: [{"pattern": "^b", "weight_decay": 0.0}]
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config()
    cfg["optimizer"] = {"type": "AdamW", "params": {"lr": 1e-2, "weight_decay": 0.05}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    assert len(engine.optimizer.param_groups) == 2
    assert engine.optimizer.param_groups[0]["weight_decay"] == 0.05
    assert engine.optimizer.param_groups[1]["weight_decay"] == 0.0
    assert engine.optimizer.param_groups[1]["lr"] == 1e-2  # inherits base lr


def test_param_groups_checkpoint_roundtrip(tmp_path):
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=_two_group_config())
    for x, y in _batches(2):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.optimizer.param_groups[1]["lr"] = 1.25e-3  # as a scheduler would
    engine.save_checkpoint(str(tmp_path))

    e2, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(1)),
        config_params=_two_group_config())
    e2.load_checkpoint(str(tmp_path))
    assert e2.optimizer.param_groups[1]["lr"] == 1.25e-3
    assert e2.optimizer.param_groups[1]["weight_decay"] == 0.0
