"""Direct unit tests for the utils/hlo.py text parsers on hand-written HLO.

The lint program passes stand on these parsers; each fixture below is a
minimal HLO fragment exercising one syntactic wrinkle the real optimizer
emits — async ``-start`` tuple conventions, nested-brace module headers,
bracketed layout types inside entry layouts — so a parser regression fails
here with a two-line diff instead of inside an engine-scale lint run.
"""

from deepspeed_tpu.utils import hlo

# async all-gather-start: (operands..., results..., u32 context scalars).
# Only the produced bf16[64] halves are wire transfers.
ASYNC_GATHER = """
HloModule m

ENTRY main {
  p0 = bf16[8]{0} parameter(0)
  p1 = bf16[8]{0} parameter(1)
  ags = (bf16[8]{0}, bf16[8]{0}, bf16[64]{0}, bf16[64]{0}, u32[], u32[]) all-gather-start(p0, p1), dimensions={0}
  agd = (bf16[64]{0}, bf16[64]{0}) all-gather-done(ags)
  ROOT out = bf16[64]{0} get-tuple-element(agd), index=0
}
"""

# all-reduce-start returns its results directly (no operand echo)
ASYNC_REDUCE = """
HloModule m

ENTRY main {
  p0 = f32[1024]{0} parameter(0)
  ars = f32[1024]{0} all-reduce-start(p0), to_apply=add
  ROOT ard = f32[1024]{0} all-reduce-done(ars)
}
"""

PERMUTE_START = """
HloModule m

ENTRY main {
  p0 = f16[32,32]{1,0} parameter(0)
  cps = (f16[32,32]{1,0}, f16[32,32]{1,0}, u32[], u32[]) collective-permute-start(p0), source_target_pairs={{0,1},{1,0}}
  ROOT cpd = f16[32,32]{1,0} collective-permute-done(cps)
}
"""

ALIAS_HEADER = """
HloModule m, input_output_alias={ {0}: (0, {}, may-alias), {2}: (1, {0}, must-alias) }, entry_computation_layout={(f32[8,8]{1,0}, bf16[64]{0}, f32[4]{0})->(f32[8,8]{1,0}, pred[], bf16[64]{0})}

ENTRY main {
  ROOT t = (f32[8,8]{1,0}, pred[], bf16[64]{0}) parameter(0)
}
"""


def test_async_all_gather_start_reports_produced_halves_only():
    types = hlo.collective_result_types(ASYNC_GATHER, "all-gather")
    assert types == ["bf16", "bf16"]
    results = hlo.collective_results(ASYNC_GATHER, "all-gather")
    assert [(dt, dims) for _op, dt, dims in results] == \
        [("bf16", (64,)), ("bf16", (64,))]
    # the -done is bookkeeping, never a second transfer
    assert hlo.collective_counts(ASYNC_GATHER) == {"all-gather": 1}


def test_async_all_reduce_start_counts_results_directly():
    assert hlo.collective_result_types(ASYNC_REDUCE, "all-reduce") == ["f32"]
    assert hlo.collective_counts(ASYNC_REDUCE) == {"all-reduce": 1}


def test_collective_permute_start_drops_context_scalars():
    results = hlo.collective_results(PERMUTE_START, "collective-permute")
    assert [(dt, dims) for _op, dt, dims in results] == [("f16", (32, 32))]


def test_collective_bytes_covers_bf16_tuples_from_start_variants():
    # 2 produced bf16[64] buffers * 2 bytes = 256
    assert hlo.collective_bytes(ASYNC_GATHER) == 2 * 64 * 2
    assert hlo.collective_bytes(ASYNC_REDUCE) == 1024 * 4


def test_dtype_bytes_table_covers_lint_element_types():
    for dt, nbytes in (("bf16", 2), ("f16", 2), ("f32", 4), ("f64", 8),
                       ("s4", 1), ("u4", 1), ("f8e4m3fn", 1), ("f8e5m2", 1),
                       ("pred", 1), ("c64", 8), ("c128", 16)):
        assert hlo.dtype_bytes(dt) == nbytes, dt
    assert hlo.dtype_bytes("token") is None


def test_input_output_aliases_parses_nested_brace_header():
    aliases = hlo.input_output_aliases(ALIAS_HEADER)
    assert aliases == {0: [((0,), (), "may-alias")],
                       1: [((2,), (0,), "must-alias")]}


def test_entry_layout_types_split_past_bracketed_layouts():
    assert hlo.entry_parameter_types(ALIAS_HEADER) == \
        [("f32", (8, 8)), ("bf16", (64,)), ("f32", (4,))]
    assert hlo.entry_result_types(ALIAS_HEADER) == \
        [("f32", (8, 8)), ("pred", ()), ("bf16", (64,))]


def test_f32_dot_probe_reads_unannotated_operands():
    # pre-backend HLO writes bare operand names with no inline types
    text = """
ENTRY main {
  a = bf16[8,16]{1,0} parameter(0)
  b = bf16[16,4]{1,0} parameter(1)
  ca = f32[8,16]{1,0} convert(a)
  cb = f32[16,4]{1,0} convert(b)
  ROOT d = f32[8,4]{1,0} dot(ca, cb), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    assert hlo.f32_dots_with_lowp_operands(text) == [("d", ["ca", "cb"])]


def test_lossy_roundtrip_detected_through_unannotated_converts():
    text = """
ENTRY main {
  a = f32[128]{0} parameter(0)
  down = bf16[128]{0} convert(a)
  up = f32[128]{0} convert(down)
  ROOT r = f32[128]{0} add(up, up)
}
"""
    assert hlo.lossy_convert_roundtrips(text) == [("down", ("f32", "bf16", "f32"))]
    # a widening detour (f32 -> f64 -> f32) is NOT lossy
    widen = text.replace("bf16[128]{0} convert(a)", "f64[128]{0} convert(a)")
    assert hlo.lossy_convert_roundtrips(widen) == []
