"""Direct unit tests for the utils/hlo.py text parsers on hand-written HLO.

The lint program passes stand on these parsers; each fixture below is a
minimal HLO fragment exercising one syntactic wrinkle the real optimizer
emits — async ``-start`` tuple conventions, nested-brace module headers,
bracketed layout types inside entry layouts — so a parser regression fails
here with a two-line diff instead of inside an engine-scale lint run.
"""

import pytest

from deepspeed_tpu.utils import hlo

# async all-gather-start: (operands..., results..., u32 context scalars).
# Only the produced bf16[64] halves are wire transfers.
ASYNC_GATHER = """
HloModule m

ENTRY main {
  p0 = bf16[8]{0} parameter(0)
  p1 = bf16[8]{0} parameter(1)
  ags = (bf16[8]{0}, bf16[8]{0}, bf16[64]{0}, bf16[64]{0}, u32[], u32[]) all-gather-start(p0, p1), dimensions={0}
  agd = (bf16[64]{0}, bf16[64]{0}) all-gather-done(ags)
  ROOT out = bf16[64]{0} get-tuple-element(agd), index=0
}
"""

# all-reduce-start returns its results directly (no operand echo)
ASYNC_REDUCE = """
HloModule m

ENTRY main {
  p0 = f32[1024]{0} parameter(0)
  ars = f32[1024]{0} all-reduce-start(p0), to_apply=add
  ROOT ard = f32[1024]{0} all-reduce-done(ars)
}
"""

PERMUTE_START = """
HloModule m

ENTRY main {
  p0 = f16[32,32]{1,0} parameter(0)
  cps = (f16[32,32]{1,0}, f16[32,32]{1,0}, u32[], u32[]) collective-permute-start(p0), source_target_pairs={{0,1},{1,0}}
  ROOT cpd = f16[32,32]{1,0} collective-permute-done(cps)
}
"""

ALIAS_HEADER = """
HloModule m, input_output_alias={ {0}: (0, {}, may-alias), {2}: (1, {0}, must-alias) }, entry_computation_layout={(f32[8,8]{1,0}, bf16[64]{0}, f32[4]{0})->(f32[8,8]{1,0}, pred[], bf16[64]{0})}

ENTRY main {
  ROOT t = (f32[8,8]{1,0}, pred[], bf16[64]{0}) parameter(0)
}
"""


def test_async_all_gather_start_reports_produced_halves_only():
    types = hlo.collective_result_types(ASYNC_GATHER, "all-gather")
    assert types == ["bf16", "bf16"]
    results = hlo.collective_results(ASYNC_GATHER, "all-gather")
    assert [(dt, dims) for _op, dt, dims in results] == \
        [("bf16", (64,)), ("bf16", (64,))]
    # the -done is bookkeeping, never a second transfer
    assert hlo.collective_counts(ASYNC_GATHER) == {"all-gather": 1}


def test_async_all_reduce_start_counts_results_directly():
    assert hlo.collective_result_types(ASYNC_REDUCE, "all-reduce") == ["f32"]
    assert hlo.collective_counts(ASYNC_REDUCE) == {"all-reduce": 1}


def test_collective_permute_start_drops_context_scalars():
    results = hlo.collective_results(PERMUTE_START, "collective-permute")
    assert [(dt, dims) for _op, dt, dims in results] == [("f16", (32, 32))]


def test_collective_bytes_covers_bf16_tuples_from_start_variants():
    # 2 produced bf16[64] buffers * 2 bytes = 256
    assert hlo.collective_bytes(ASYNC_GATHER) == 2 * 64 * 2
    assert hlo.collective_bytes(ASYNC_REDUCE) == 1024 * 4


def test_dtype_bytes_table_covers_lint_element_types():
    for dt, nbytes in (("bf16", 2), ("f16", 2), ("f32", 4), ("f64", 8),
                       ("s4", 1), ("u4", 1), ("f8e4m3fn", 1), ("f8e5m2", 1),
                       ("pred", 1), ("c64", 8), ("c128", 16)):
        assert hlo.dtype_bytes(dt) == nbytes, dt
    assert hlo.dtype_bytes("token") is None


def test_input_output_aliases_parses_nested_brace_header():
    aliases = hlo.input_output_aliases(ALIAS_HEADER)
    assert aliases == {0: [((0,), (), "may-alias")],
                       1: [((2,), (0,), "must-alias")]}


def test_entry_layout_types_split_past_bracketed_layouts():
    assert hlo.entry_parameter_types(ALIAS_HEADER) == \
        [("f32", (8, 8)), ("bf16", (64,)), ("f32", (4,))]
    assert hlo.entry_result_types(ALIAS_HEADER) == \
        [("f32", (8, 8)), ("pred", ()), ("bf16", (64,))]


# post-scheduling overlap window: compute placed between -start and -done,
# explicit replica groups on the start line
OVERLAP_WINDOW = """
HloModule m

ENTRY main {
  p0 = f32[1024]{0} parameter(0)
  a = f32[64,64]{1,0} parameter(1)
  b = f32[64,64]{1,0} parameter(2)
  ars = f32[1024]{0} all-reduce-start(p0), replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=add
  d = f32[64,64]{1,0} dot(f32[64,64]{1,0} a, f32[64,64]{1,0} b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ard = f32[1024]{0} all-reduce-done(f32[1024]{0} ars)
  ROOT out = f32[64,64]{1,0} add(d, d)
}
"""

# iota replica-group form on an async all-gather
IOTA_ASYNC = """
HloModule m

ENTRY main {
  p0 = bf16[8]{0} parameter(0)
  ags = (bf16[8]{0}, bf16[32]{0}, u32[], u32[]) all-gather-start(p0), replica_groups=[2,4]<=[8], dimensions={0}
  ROOT agd = bf16[32]{0} all-gather-done(ags)
}
"""

# generic async wrapper: the collective lives in a called computation, and the
# done chains to the start through an async-update
NESTED_ASYNC = """
HloModule m

%wrapped_ag (param_0: bf16[8]) -> bf16[64] {
  %param_0 = bf16[8]{0} parameter(0)
  ROOT %ag = bf16[64]{0} all-gather(%param_0), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
}

ENTRY main {
  p0 = bf16[8]{0} parameter(0)
  %ag-start = ((bf16[8]{0}), bf16[64]{0}, u32[]) async-start(p0), calls=%wrapped_ag
  %ag-upd = ((bf16[8]{0}), bf16[64]{0}, u32[]) async-update(%ag-start)
  ROOT %ag-done = bf16[64]{0} async-done(%ag-upd)
}
"""

UNMATCHED_DONE = """
HloModule m

ENTRY main {
  p0 = f32[16]{0} parameter(0)
  ROOT bad = f32[16]{0} all-reduce-done(p0)
}
"""


def test_parse_async_pairs_dedicated_forms():
    (pair,) = hlo.parse_async_pairs(ASYNC_REDUCE)
    assert pair["op"] == "all-reduce" and pair["name"] == "ars"
    assert pair["bytes"] == 1024 * 4 and pair["groups"] is None
    assert pair["start_line"] < pair["done_line"]
    (gpair,) = hlo.parse_async_pairs(ASYNC_GATHER)
    # produced halves only, same convention as collective_bytes
    assert gpair["op"] == "all-gather" and gpair["bytes"] == 2 * 64 * 2
    (ppair,) = hlo.parse_async_pairs(PERMUTE_START)
    assert ppair["op"] == "collective-permute"
    assert ppair["groups"] == [(0, 1), (1, 0)]


def test_parse_async_pairs_explicit_groups_and_window():
    (pair,) = hlo.parse_async_pairs(OVERLAP_WINDOW)
    assert pair["groups"] == [(0, 1, 2, 3), (4, 5, 6, 7)]
    lines = OVERLAP_WINDOW.splitlines()
    window = lines[pair["start_line"] + 1:pair["done_line"]]
    assert len(window) == 1 and " dot(" in window[0]


def test_parse_async_pairs_iota_groups():
    (pair,) = hlo.parse_async_pairs(IOTA_ASYNC)
    assert pair["groups"] == [(0, 1, 2, 3), (4, 5, 6, 7)]
    assert pair["bytes"] == 32 * 2  # the produced bf16[32] half only


def test_parse_async_pairs_nested_wrapper():
    (pair,) = hlo.parse_async_pairs(NESTED_ASYNC)
    assert pair["op"] == "all-gather"
    assert pair["name"] == "ag-start" and pair["done"] == "ag-done"
    assert pair["bytes"] == 64 * 2
    assert pair["groups"] == [(0, 1, 2, 3, 4, 5, 6, 7)]


def test_parse_async_pairs_unmatched_done_raises():
    with pytest.raises(ValueError, match="no matching -start"):
        hlo.parse_async_pairs(UNMATCHED_DONE)


def test_dot_flops_estimate_reads_annotated_operands():
    line = ("  d = f32[64,64]{1,0} dot(f32[64,64]{1,0} a, f32[64,64]{1,0} b), "
            "lhs_contracting_dims={1}, rhs_contracting_dims={0}")
    assert hlo.dot_flops_estimate(line) == 2 * 64 * 64 * 64
    # unannotated operands give no phantom compute credit
    assert hlo.dot_flops_estimate(
        "  d = f32[8,4]{1,0} dot(ca, cb), lhs_contracting_dims={1}") == 0
    assert hlo.dot_flops_estimate("  a = f32[8]{0} add(x, y)") == 0


def test_result_bytes_reads_the_definition_type():
    assert hlo.result_bytes("  p = f32[128]{0} parameter(0)") == 512
    assert hlo.result_bytes(
        "  t = (bf16[64]{0}, bf16[64]{0}) all-gather-done(x)") == 256
    assert hlo.result_bytes("ENTRY main {") == 0


def test_f32_dot_probe_reads_unannotated_operands():
    # pre-backend HLO writes bare operand names with no inline types
    text = """
ENTRY main {
  a = bf16[8,16]{1,0} parameter(0)
  b = bf16[16,4]{1,0} parameter(1)
  ca = f32[8,16]{1,0} convert(a)
  cb = f32[16,4]{1,0} convert(b)
  ROOT d = f32[8,4]{1,0} dot(ca, cb), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    assert hlo.f32_dots_with_lowp_operands(text) == [("d", ["ca", "cb"])]


def test_lossy_roundtrip_detected_through_unannotated_converts():
    text = """
ENTRY main {
  a = f32[128]{0} parameter(0)
  down = bf16[128]{0} convert(a)
  up = f32[128]{0} convert(down)
  ROOT r = f32[128]{0} add(up, up)
}
"""
    assert hlo.lossy_convert_roundtrips(text) == [("down", ("f32", "bf16", "f32"))]
    # a widening detour (f32 -> f64 -> f32) is NOT lossy
    widen = text.replace("bf16[128]{0} convert(a)", "f64[128]{0} convert(a)")
    assert hlo.lossy_convert_roundtrips(widen) == []
