"""SPMD pipe-axis pipeline tests: numerics vs sequential, grads, GPT2Pipe end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.pipeline_spmd import (pipeline_apply, stack_stage_params,
                                                  stacked_param_sharding)

S, M, B, H = 2, 4, 8, 16

# Forward-only pipeline paths work on every jax; grad-through-pipeline needs
# the top-level jax.shard_map (see tests/unit/oldjax.py).
from oldjax import grad_through_shard_map_xfail as grad_through_pipeline_xfail


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(data=4, model=1, pipe=2)


@pytest.fixture(scope="module")
def toy(mesh):
    key = jax.random.PRNGKey(0)
    per_stage = []
    for _ in range(S):
        k1, key = jax.random.split(key)
        per_stage.append({"w": jax.random.normal(k1, (H, H)) * 0.3, "b": jnp.zeros((H,))})
    stacked = stack_stage_params(per_stage)
    stacked = jax.device_put(stacked, stacked_param_sharding(mesh, stacked))
    x_mb = jax.random.normal(key, (M, B, H))
    labels_mb = jnp.tanh(x_mb @ (jax.random.normal(jax.random.PRNGKey(9), (H, H)) * 0.5))
    return stacked, x_mb, labels_mb


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def seq_loss(stacked, x_mb, labels_mb):
    losses = []
    for m in range(M):
        x = x_mb[m]
        for s in range(S):
            x = stage_fn(jax.tree_util.tree_map(lambda a: a[s], stacked), x)
        losses.append(jnp.mean((x - labels_mb[m])**2))
    return jnp.mean(jnp.stack(losses))


def test_pipeline_forward_matches_sequential(mesh, toy):
    stacked, x_mb, _ = toy
    outs = jax.jit(lambda s, x: pipeline_apply(stage_fn, s, x, mesh=mesh))(stacked, x_mb)
    ref = jnp.stack([
        stage_fn(jax.tree_util.tree_map(lambda a: a[1], stacked),
                 stage_fn(jax.tree_util.tree_map(lambda a: a[0], stacked), x_mb[m]))
        for m in range(M)
    ])
    np.testing.assert_allclose(np.asarray(outs), np.asarray(ref), rtol=1e-5, atol=1e-6)


@grad_through_pipeline_xfail
def test_pipeline_loss_and_grads_match_sequential(mesh, toy):
    stacked, x_mb, labels_mb = toy

    def last_fn(y, labels_all, mb):
        return jnp.mean((y - labels_all[mb])**2)

    def pipe_loss(stacked, x_mb):
        from jax.sharding import PartitionSpec as P
        return pipeline_apply(stage_fn, stacked, x_mb, mesh=mesh,
                              last_stage_fn=last_fn, last_stage_args=(labels_mb,),
                              last_stage_args_specs=(P(None, "data"),))

    l_seq = jax.jit(lambda s, x: seq_loss(s, x, labels_mb))(stacked, x_mb)
    l_pipe = jax.jit(pipe_loss)(stacked, x_mb)
    np.testing.assert_allclose(float(l_seq), float(l_pipe), rtol=1e-6)

    g_seq = jax.jit(jax.grad(lambda s, x: seq_loss(s, x, labels_mb)))(stacked, x_mb)
    g_pipe = jax.jit(jax.grad(pipe_loss))(stacked, x_mb)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_seq[k]), np.asarray(g_pipe[k]),
                                   rtol=1e-5, atol=1e-6)


def test_ambiguous_last_stage_args_refused_without_specs(mesh, toy):
    """A last_stage_args leaf whose leading dim == M is ambiguous (micro-batched
    labels vs a weight that coincidentally matches); the default streamed path must
    refuse and name the leaf — same contract as the drain-per-flush schedule —
    instead of silently guessing data-sharded (ADVICE r5 medium)."""
    stacked, x_mb, labels_mb = toy

    def last_fn(y, labels_all, mb):
        return jnp.mean((y - labels_all[mb])**2)

    with pytest.raises(ValueError, match=r"last_stage_args leaf .* leading dim == M"):
        jax.jit(lambda s, x: pipeline_apply(
            stage_fn, s, x, mesh=mesh, last_stage_fn=last_fn,
            last_stage_args=(labels_mb,)))(stacked, x_mb)

    # an unambiguous extra arg (no M-leading dim) still infers P() without specs
    scale = jnp.float32(2.0)

    def last_fn2(y, s, mb):
        return s * jnp.mean(y**2)

    l_ok = jax.jit(lambda s, x: pipeline_apply(
        stage_fn, s, x, mesh=mesh, last_stage_fn=last_fn2,
        last_stage_args=(scale,)))(stacked, x_mb)
    assert np.isfinite(float(l_ok))


def test_stacked_params_actually_pipe_sharded(mesh, toy):
    stacked, _, _ = toy
    sh = stacked["w"].sharding
    assert not sh.is_fully_replicated


@grad_through_pipeline_xfail
def test_gpt2_pipe_trains(mesh):
    """Full 3D slice: GPT2Pipe (pipe=2 stages x data=4 DP x ZeRO-2) through the engine."""
    from deepspeed_tpu.models.gpt2 import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import GPT2Pipe
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=32, n_layer=4, n_head=2,
                     compute_dtype=jnp.float32)
    pipe = GPT2Pipe(cfg, num_stages=2)
    params = pipe.init(jax.random.PRNGKey(0))
    shardings = pipe.param_shardings(mesh, params)

    def model_fn(p, tokens_mb, labels_mb):
        return pipe.loss(p, tokens_mb, labels_mb, mesh=mesh)

    # all M micro-batches run inside one engine call (the pipeline IS the accumulation)
    ds_cfg = {"train_batch_size": 8 * M, "train_micro_batch_size_per_gpu": 2 * M,
              "gradient_accumulation_steps": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 2}, "steps_per_print": 100}
    engine = DeepSpeedEngine(model=model_fn, model_parameters=params, config_params=ds_cfg,
                             mesh=mesh, param_shardings=shardings)

    rng = np.random.default_rng(0)
    data_spec = NamedSharding(mesh, P(None, "data"))
    # overfit one fixed batch: loss must drop (random fresh tokens would be irreducible)
    toks = rng.integers(0, cfg.vocab_size, size=(M, 8, 16)).astype(np.int32)
    labels = np.roll(toks, -1, axis=2)
    toks = jax.device_put(jnp.asarray(toks), data_spec)
    labels = jax.device_put(jnp.asarray(labels), data_spec)
    losses = []
    for step in range(8):
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert engine.global_steps == 8, "every call must fire an optimizer update"
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], f"{losses}"
    # stacked block weights keep pipe sharding through the update
    assert not engine.master_params["stages"]["attn"]["c_attn_w"].sharding.is_fully_replicated


def test_per_rank_param_bytes_scale_with_stages():
    """VERDICT #6: the tied vocab table shards over pipe (vocab-parallel embed/head),
    so per-pipe-rank parameter bytes ∝ 1/S INCLUDING the embedding — no leaf may be
    replicated over pipe except the small ln_f/wpe extras."""
    from deepspeed_tpu.models.gpt2 import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import GPT2Pipe
    from deepspeed_tpu.parallel.mesh import build_mesh

    cfg = GPT2Config(vocab_size=512, n_layer=4, n_head=2, n_embd=64, n_positions=64)
    S = 4
    mesh = build_mesh(data=2, model=1, pipe=S)
    pipe = GPT2Pipe(cfg, num_stages=S)
    params = pipe.init(jax.random.PRNGKey(0))
    sh = pipe.param_shardings(mesh, params)
    placed = jax.device_put(params, sh)

    total = sum(l.nbytes for l in jax.tree_util.tree_leaves(placed))
    dev0 = mesh.devices.ravel()[0]
    per_dev = 0
    for leaf in jax.tree_util.tree_leaves(placed):
        for s in leaf.addressable_shards:
            if s.device == dev0:
                per_dev += s.data.nbytes
    # replicated-over-pipe extras: wpe [T, E] + ln_f scale/bias
    extras = placed["io"]["wpe"].nbytes + sum(
        l.nbytes for l in jax.tree_util.tree_leaves(placed["io"]["ln_f"]))
    assert per_dev <= total / S + extras + 1024, (per_dev, total / S, extras)
    # and specifically the vocab table is split over pipe
    wte = placed["io"]["wte"]
    shard_rows = {s.data.shape[0] for s in wte.addressable_shards}
    assert shard_rows == {cfg.vocab_size // S}, shard_rows


def test_gpt2_pipe_odd_vocab_matches_dense():
    """A GPT-2-style odd vocab (not divisible by num_stages) must pad the pipe-sharded
    table internally and still produce the DENSE model's exact loss (padded logit
    columns masked out of the vocab-parallel softmax)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.models.gpt2_pipe import GPT2Pipe
    from deepspeed_tpu.parallel.mesh import build_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = GPT2Config(vocab_size=131, n_positions=32, n_embd=32, n_layer=4, n_head=2,
                     compute_dtype=jnp.float32)
    mesh = build_mesh(data=2, model=1, pipe=4)
    dense = GPT2Model(cfg)
    dense_params = dense.init(jax.random.PRNGKey(3))
    pipe = GPT2Pipe(cfg, num_stages=4)
    params = pipe.from_dense(dense_params)
    assert params["io"]["wte"].shape[0] == 132  # padded to a stage multiple
    placed = jax.device_put(params, pipe.param_shardings(mesh, params))

    rng = np.random.default_rng(1)
    toks = rng.integers(0, cfg.vocab_size, size=(2, 4, 16)).astype(np.int32)
    labels = np.roll(toks, -1, axis=2)
    spec = NamedSharding(mesh, P(None, "data"))
    toks_d = jax.device_put(jnp.asarray(toks), spec)
    labels_d = jax.device_put(jnp.asarray(labels), spec)
    pipe_loss = float(jax.device_get(pipe.loss(placed, toks_d, labels_d, mesh=mesh)))

    dense_losses = [float(jax.device_get(dense.apply(dense_params, jnp.asarray(toks[m]),
                                                     jnp.asarray(labels[m]))))
                    for m in range(2)]
    np.testing.assert_allclose(pipe_loss, np.mean(dense_losses), rtol=1e-5)


@pytest.mark.parametrize("tp", [1, 2])
def test_gpt2_pipe_to_dense_roundtrip(tp):
    """to_dense must invert _stack exactly — vocab padding stripped, qkv permutation
    undone — so checkpoints can move across (num_stages, tp) topologies (ADVICE r2)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.models.gpt2_pipe import GPT2Pipe

    cfg = GPT2Config(vocab_size=131, n_positions=32, n_embd=32, n_layer=4, n_head=2,
                     compute_dtype=jnp.float32)
    dense_params = GPT2Model(cfg).init(jax.random.PRNGKey(5))
    pipe = GPT2Pipe(cfg, num_stages=2, tp=tp)
    stacked = pipe.from_dense(dense_params)
    assert stacked["io"]["wte"].shape[0] == 132  # stage-padded inside the stacked tree
    back = pipe.to_dense(stacked)
    assert back["wte"].shape[0] == cfg.vocab_size  # padding stripped on export
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0),
        dense_params, back)
    # and the dense tree reloads onto a DIFFERENT topology
    pipe4 = GPT2Pipe(cfg, num_stages=4)
    restacked = pipe4.from_dense(back)
    assert restacked["io"]["wte"].shape[0] == 132


@grad_through_pipeline_xfail
@pytest.mark.parametrize("streamed", [True, False])
def test_auto_flush_split_matches_single_flush(mesh, streamed):
    """M = 8S must auto-split into rematerialized segments (VERDICT r2 next #5) with
    bit-comparable loss AND grads vs the unsplit pipeline — in BOTH the streamed
    (single-fill, default) and the legacy drain-per-flush schedule. The grad check
    covers every segment-boundary micro-batch (the streamed carry's hard case)."""
    from jax.sharding import PartitionSpec as P
    S2, M8 = 2, 16
    key = jax.random.PRNGKey(2)
    per_stage = []
    for _ in range(S2):
        k1, key = jax.random.split(key)
        per_stage.append({"w": jax.random.normal(k1, (H, H)) * 0.3, "b": jnp.zeros((H,))})
    stacked = stack_stage_params(per_stage)
    stacked = jax.device_put(stacked, stacked_param_sharding(mesh, stacked))
    x_mb = jax.random.normal(key, (M8, B, H))
    labels_mb = jnp.tanh(x_mb @ (jax.random.normal(jax.random.PRNGKey(3), (H, H)) * 0.5))

    def last_fn(y, labels_all, mb):
        return jnp.mean((y - labels_all[mb])**2)

    def loss(cap):
        def f(s, x):
            return pipeline_apply(stage_fn, s, x, mesh=mesh, last_stage_fn=last_fn,
                                  last_stage_args=(labels_mb,),
                                  last_stage_args_specs=(P(None, "data"),),
                                  max_microbatches_per_flush=cap,
                                  stream_segments=streamed)
        return f

    l_split = jax.jit(loss(None))(stacked, x_mb)       # default cap 4*S=8 < M: splits
    l_whole = jax.jit(loss(0))(stacked, x_mb)          # splitting disabled
    np.testing.assert_allclose(float(l_split), float(l_whole), rtol=1e-6)

    g_split = jax.jit(jax.grad(loss(None)))(stacked, x_mb)
    g_whole = jax.jit(jax.grad(loss(0)))(stacked, x_mb)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_split[k]), np.asarray(g_whole[k]),
                                   rtol=1e-5, atol=1e-6)


def test_flush_schedule_accounting():
    """Step accounting: the streamed schedule pays the single (S-1)-step fill once
    (the reference 1F1B discipline, schedule.py:182-289); the legacy schedule pays
    it per flush."""
    from deepspeed_tpu.parallel.pipeline_spmd import flush_schedule

    acc = flush_schedule(M=128, S=8, cap=32, streamed=True)
    assert acc == {"steps": 135, "ideal_steps": 135, "n_segments": 4,
                   "bubble_fraction": acc["bubble_fraction"]}
    assert abs(acc["bubble_fraction"] - (1 - 128 / 135)) < 1e-12
    legacy = flush_schedule(M=128, S=8, cap=32, streamed=False)
    assert legacy["steps"] == 4 * (32 + 7) == 156
    assert legacy["bubble_fraction"] > 0.17 > acc["bubble_fraction"]

    with pytest.raises(AssertionError):
        flush_schedule(M=10, S=2, cap=4)


def _scan_lengths(jaxpr):
    """All (length, has_stage_marker) for scan eqns anywhere in a jaxpr, where the
    marker is whether the scan body applies the stage function (detected via a
    sentinel primitive-free probe: we instead return raw lengths and let the
    caller reason about them)."""
    def as_jaxpr(v):
        # ClosedJaxpr wraps .jaxpr; raw Jaxpr (shard_map/remat bodies) has .eqns
        if hasattr(v, "eqns"):
            return v
        inner = getattr(v, "jaxpr", None)
        return inner if hasattr(inner, "eqns") else None

    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(eqn.params["length"])
        for v in eqn.params.values():
            for w in (v if isinstance(v, (list, tuple)) else [v]):
                j = as_jaxpr(w)
                if j is not None:
                    out.extend(_scan_lengths(j))
    return out


def test_streamed_executes_single_fill_step_count(mesh):
    """The TRACED streamed program's scan trip counts prove the single-fill
    schedule: an n-segment outer scan whose body runs `cap` pipeline steps, plus
    one (S-1)-step drain — total executed steps == flush_schedule(streamed)
    == M + S - 1, NOT the legacy n*(cap+S-1). A regression that drains per
    segment would show an inner length of cap+S-1 (or an extra S-1 scan per
    segment) and fail the exact-multiset assertion."""
    from deepspeed_tpu.parallel.pipeline_spmd import flush_schedule
    S2, M8, cap = 2, 16, 8

    key = jax.random.PRNGKey(2)
    per_stage = []
    for _ in range(S2):
        k1, key = jax.random.split(key)
        per_stage.append({"w": jax.random.normal(k1, (H, H)) * 0.3, "b": jnp.zeros((H,))})
    stacked = stack_stage_params(per_stage)
    stacked = jax.device_put(stacked, stacked_param_sharding(mesh, stacked))
    x_mb = jax.random.normal(key, (M8, B, H))

    def last_fn(y, mb):
        return jnp.mean(y)

    def f(s, x):
        return pipeline_apply(stage_fn, s, x, mesh=mesh, last_stage_fn=last_fn,
                              max_microbatches_per_flush=cap)

    lengths = sorted(_scan_lengths(jax.make_jaxpr(f)(stacked, x_mb).jaxpr))
    n = M8 // cap
    # exactly three scans: drain (S-1), segment body (cap), outer segments (n)
    assert lengths == sorted([S2 - 1, cap, n]), lengths
    # executed pipeline steps = n * cap + (S - 1) = the single-fill optimum
    acc = flush_schedule(M=M8, S=S2, cap=cap, streamed=True)
    assert n * cap + (S2 - 1) == acc["steps"] == M8 + S2 - 1

    # the legacy schedule shows its drain in the trip counts: inner flush scans
    # run cap + S - 1 steps each
    def f_legacy(s, x):
        return pipeline_apply(stage_fn, s, x, mesh=mesh, last_stage_fn=last_fn,
                              max_microbatches_per_flush=cap, stream_segments=False)

    legacy_lengths = sorted(_scan_lengths(jax.make_jaxpr(f_legacy)(stacked, x_mb).jaxpr))
    assert cap + S2 - 1 in legacy_lengths, legacy_lengths
    assert n * (cap + S2 - 1) == flush_schedule(M8, S2, cap, streamed=False)["steps"]


def test_auto_flush_split_through_gpt2_pipe(mesh):
    """GPT2Pipe at M = 8S (vocab-parallel embedding/head + collective last stage)
    still matches the dense model under the flush splitter."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.models.gpt2_pipe import GPT2Pipe
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = GPT2Config(vocab_size=64, n_positions=16, n_embd=16, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32)
    dense = GPT2Model(cfg)
    dense_params = dense.init(jax.random.PRNGKey(4))
    pipe = GPT2Pipe(cfg, num_stages=2)
    params = pipe.from_dense(dense_params)
    placed = jax.device_put(params, pipe.param_shardings(mesh, params))

    M8 = 16  # 8 * num_stages -> two flushes of 8
    rng = np.random.default_rng(5)
    toks = rng.integers(0, cfg.vocab_size, size=(M8, 4, 8)).astype(np.int32)
    labels = np.roll(toks, -1, axis=2)
    spec = NamedSharding(mesh, P(None, "data"))
    toks_d = jax.device_put(jnp.asarray(toks), spec)
    labels_d = jax.device_put(jnp.asarray(labels), spec)
    pipe_loss = float(jax.device_get(pipe.loss(placed, toks_d, labels_d, mesh=mesh)))
    dense_losses = [float(jax.device_get(dense.apply(dense_params, jnp.asarray(toks[m]),
                                                     jnp.asarray(labels[m]))))
                    for m in range(M8)]
    np.testing.assert_allclose(pipe_loss, np.mean(dense_losses), rtol=1e-5)
