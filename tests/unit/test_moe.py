"""Mixture-of-Experts + expert parallelism on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.moe import MoELayer, moe_apply_sharded

H, F, E = 16, 32, 8


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(data=1, model=8, pipe=1)


def oracle(layer, params, x2):
    """Per-token reference: each token through its argmax expert's MLP, weighted
    by the gate prob (assumes capacity large enough that nothing drops)."""
    logits = x2.astype(np.float32) @ np.asarray(params["gate_w"])
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    idx = np.argmax(np.asarray(probs), axis=-1)
    out = np.zeros_like(np.asarray(x2, np.float32))
    for n, e in enumerate(idx):
        h = np.asarray(x2[n], np.float32) @ np.asarray(params["w_in"][e]) + \
            np.asarray(params["b_in"][e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        y = h @ np.asarray(params["w_out"][e]) + np.asarray(params["b_out"][e])
        out[n] = float(np.asarray(probs)[n, e]) * y
    return out


def test_dense_dispatch_matches_per_token_oracle():
    layer = MoELayer(H, F, E, capacity_factor=8.0)  # no drops
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (24, H), jnp.float32)
    y, aux = layer.apply(params, x)
    np.testing.assert_allclose(np.asarray(y), oracle(layer, params, x),
                               rtol=1e-4, atol=1e-5)
    assert float(aux) > 0  # E * sum f*p >= 1 by Cauchy-Schwarz, > 0 always


def test_capacity_drops_overflow_tokens():
    """With capacity 1 per expert, later tokens routed to a full expert must
    produce ZERO output (they ride the residual in a real block)."""
    layer = MoELayer(H, F, E, capacity_factor=1e-9)  # capacity clamps to 1
    params = layer.init(jax.random.PRNGKey(0))
    # two identical tokens route to the same expert; the second must drop
    x = jnp.tile(jax.random.normal(jax.random.PRNGKey(2), (1, H)), (2, 1))
    y, _ = layer.apply(params, x)
    assert not np.allclose(np.asarray(y[0]), 0.0)
    np.testing.assert_allclose(np.asarray(y[1]), 0.0, atol=1e-7)


def test_expert_parallel_matches_dense_dispatch(mesh):
    """8-way expert-parallel (all_to_all dispatch) must equal the single-program
    dense dispatch bit-for-bit at fp32 — fwd AND grads."""
    dense = MoELayer(H, F, E, capacity_factor=8.0)
    ep = MoELayer(H, F, E, capacity_factor=8.0, expert_axis="model")
    params = dense.init(jax.random.PRNGKey(3))
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 8, H), jnp.float32)

    y_d, aux_d = dense.apply(params, x)
    y_p, aux_p = moe_apply_sharded(ep, mesh, params, x)
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_d), rtol=2e-5,
                               atol=2e-6)
    np.testing.assert_allclose(float(aux_p), float(aux_d), rtol=1e-5)

    def loss_d(p):
        y, aux = dense.apply(p, x)
        return jnp.sum(y ** 2) + 0.01 * aux

    def loss_p(p):
        y, aux = moe_apply_sharded(ep, mesh, p, x)
        return jnp.sum(y ** 2) + 0.01 * aux

    g_d = jax.grad(loss_d)(params)
    g_p = jax.grad(loss_p)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=5e-4, atol=1e-5),
        g_p, g_d)


def test_expert_parallel_emits_all_to_all(mesh):
    from deepspeed_tpu.utils.hlo import collective_counts, optimized_hlo

    ep = MoELayer(H, F, E, capacity_factor=2.0, expert_axis="model")
    params = ep.init(jax.random.PRNGKey(5))
    x = jnp.zeros((4, 8, H), jnp.float32)
    j = jax.jit(lambda p, x: moe_apply_sharded(ep, mesh, p, x)[0])
    counts = collective_counts(optimized_hlo(j, params, x))
    assert counts.get("all-to-all", 0) >= 2, \
        f"EP dispatch+return should be two all_to_alls: {counts}"


def test_moe_trains_through_engine(mesh):
    """A 2-layer MoE MLP regression model trains through DeepSpeedEngine with the
    aux loss added — loss decreases (experts + gate learn)."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    ep = MoELayer(H, F, E, capacity_factor=2.0, expert_axis="model")

    class Model:
        def init(self, rng):
            k1, k2 = jax.random.split(rng)
            return {"moe": ep.init(k1),
                    "head": jax.random.normal(k2, (H, H), jnp.float32) * 0.3}

        def apply(self, params, x, y):
            h, aux = moe_apply_sharded(ep, mesh, params["moe"], x)
            pred = jnp.tanh(h) @ params["head"]
            return jnp.mean((pred - y) ** 2) + 0.01 * aux

    model = Model()
    engine = DeepSpeedEngine(
        model=model, model_parameters=model.init(jax.random.PRNGKey(6)), mesh=mesh,
        config_params={"train_batch_size": 32, "train_micro_batch_size_per_gpu": 32,
                       "gradient_accumulation_steps": 1, "steps_per_print": 100,
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(H, H)).astype(np.float32) * 0.4
    losses = []
    for _ in range(50):
        x = rng.normal(size=(32, H)).astype(np.float32)
        y = np.tanh(x @ w_true)
        loss = engine(jnp.asarray(x), jnp.asarray(y))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


@pytest.mark.slow  # compile-bound integration (~17s); tier-1 870s cap
def test_gpt2_moe_trains_through_engine():
    """GPT2Config(moe_experts=..) alternates switch-MoE FFN blocks; the model
    trains through DeepSpeedEngine with ZeRO-2 and the aux loss folded in."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32, n_layer=4, n_head=2,
                     compute_dtype=jnp.float32, moe_experts=4, moe_every=2,
                     moe_capacity_factor=2.0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    assert "moe" in params["blocks"][1] and "mlp" in params["blocks"][0]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config_params={"train_batch_size": 16, "steps_per_print": 100,
                       "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                       "zero_optimization": {"stage": 2}})
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 128, size=(16, 64)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    losses = []
    for _ in range(25):
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_gpt2_moe_gspmd_expert_sharding_matches_replicated(mesh):
    """GSPMD expert parallelism: expert weights sharded over 'model' must give the
    same loss/grads as fully replicated params (XLA partitions the batched expert
    einsums; the math is identical)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32, moe_experts=8, moe_every=1,
                     moe_capacity_factor=4.0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(3))
    toks = jnp.asarray(np.random.default_rng(2).integers(0, 64, (4, 32)), jnp.int32)
    labels = jnp.roll(toks, -1, axis=1)

    l_repl = float(jax.jit(model.apply)(params, toks, labels))
    sh = model.param_shardings(mesh)
    assert not sh["blocks"][0]["moe"]["w_in"].is_fully_replicated
    params_sh = jax.device_put(params, sh)
    l_shard = float(jax.jit(model.apply)(params_sh, toks, labels))
    np.testing.assert_allclose(l_shard, l_repl, rtol=2e-5)

    g_r = jax.jit(jax.grad(model.apply))(params, toks, labels)
    g_s = jax.jit(jax.grad(model.apply))(params_sh, toks, labels)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=5e-4, atol=1e-5),
        g_s, g_r)


@pytest.mark.slow  # 8-rank interpret ring + MoE (~61s); tier-1 870s cap
def test_gpt2_moe_composes_with_sequence_parallelism():
    """MoE + ring-attention sequence parallelism: dense dispatch routes each
    rank's local chunk (per-chunk capacity), aux folds into the pmean'd loss,
    and the sp loss matches the dense model when capacity is ample."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    sp_mesh = build_mesh(data=8, model=1, pipe=1)
    # aux weight 0 for the exact-parity check: the TASK loss is identical with
    # ample capacity; the aux term differs at second order (per-chunk E*sum(f·p)
    # means over ranks vs global statistics)
    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=2,
                     compute_dtype=jnp.float32, moe_experts=4, moe_every=1,
                     moe_capacity_factor=8.0, moe_aux_weight=0.0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(7))
    toks = jnp.asarray(np.random.default_rng(5).integers(0, 64, (2, 64)), jnp.int32)
    labels = jnp.roll(toks, -1, axis=1)
    sp_loss = model.sequence_parallel_loss_fn(sp_mesh, "data")
    l_sp = float(jax.jit(sp_loss)(params, toks, labels))
    l_ref = float(model.apply(params, toks, labels))
    np.testing.assert_allclose(l_sp, l_ref, rtol=2e-5)

    # with the aux term on, sp and dense agree closely (the balancing statistics
    # are chunk-local) and grads stay finite
    cfg2 = GPT2Config(vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=2,
                      compute_dtype=jnp.float32, moe_experts=4, moe_every=1,
                      moe_capacity_factor=8.0)
    model2 = GPT2Model(cfg2)
    sp_loss2 = model2.sequence_parallel_loss_fn(sp_mesh, "data")
    l_sp2 = float(jax.jit(sp_loss2)(params, toks, labels))
    np.testing.assert_allclose(l_sp2, float(model2.apply(params, toks, labels)),
                               rtol=1e-3)
    g = jax.jit(jax.grad(sp_loss2))(params, toks, labels)
    assert all(bool(jnp.isfinite(x).all()) for x in jax.tree_util.tree_leaves(g))


def test_grouped_routing_matches_ungrouped_outputs():
    """Grouped dispatch (the O(N*g) memory form) must produce the same outputs as
    one whole-batch group when capacity is ample — only the aux statistics are
    computed per group."""
    dense = MoELayer(H, F, E, capacity_factor=8.0)
    grouped = MoELayer(H, F, E, capacity_factor=8.0, group_size=8)
    params = dense.init(jax.random.PRNGKey(9))
    x = jax.random.normal(jax.random.PRNGKey(10), (32, H), jnp.float32)
    y_d, _ = dense.apply(params, x)
    y_g, aux_g = grouped.apply(params, x)
    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_d), rtol=1e-5,
                               atol=1e-6)
    assert float(aux_g) > 0


def test_top2_gshard_matches_per_token_oracle():
    """top_k=2 (GShard): each token through its two highest-prob experts, gate
    weights normalized over the pair — per-token oracle parity with ample
    capacity; top-2 also runs through the expert-parallel path."""
    layer = MoELayer(H, F, E, capacity_factor=16.0, top_k=2)
    params = layer.init(jax.random.PRNGKey(11))
    x = jax.random.normal(jax.random.PRNGKey(12), (24, H), jnp.float32)
    y, aux = layer.apply(params, x)

    probs = np.asarray(jax.nn.softmax(
        jnp.asarray(np.asarray(x) @ np.asarray(params["gate_w"])), axis=-1))
    ref = np.zeros((24, H), np.float32)
    for n in range(24):
        order = np.argsort(-probs[n])
        e1, e2 = int(order[0]), int(order[1])
        denom = probs[n, e1] + probs[n, e2]
        for e, w in ((e1, probs[n, e1] / denom), (e2, probs[n, e2] / denom)):
            h = np.asarray(x[n]) @ np.asarray(params["w_in"][e]) + \
                np.asarray(params["b_in"][e])
            h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
            ref[n] += w * (h @ np.asarray(params["w_out"][e]) +
                           np.asarray(params["b_out"][e]))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)
    assert float(aux) > 0


@pytest.mark.parametrize(
    "top_k", [1, pytest.param(2, marks=pytest.mark.slow)])
def test_scatter_dispatch_matches_einsum(mesh, top_k):
    """The scatter/gather dispatch (row scatter-add + row gather — flops-cheap,
    but slower than the default einsum on TPU, see PERF.md)
    must reproduce the dense one-hot einsum dispatch bit-for-bit in fp32 —
    dense apply, tight capacity (drops exercised), and the expert-parallel
    all_to_all path; gradients too."""
    cf = 0.6  # tight: forces capacity drops both modes must agree on
    kw = dict(hidden=H, ffn_dim=F, num_experts=E, capacity_factor=cf, top_k=top_k)
    l_sc = MoELayer(**kw, dispatch="scatter")
    l_ei = MoELayer(**kw, dispatch="einsum")
    params = l_sc.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, H), jnp.float32)

    y_sc, aux_sc = l_sc.apply(params, x)
    y_ei, aux_ei = l_ei.apply(params, x)
    np.testing.assert_allclose(np.asarray(y_sc), np.asarray(y_ei),
                               rtol=1e-5, atol=1e-5)
    assert float(aux_sc) == pytest.approx(float(aux_ei))

    g_sc = jax.grad(lambda p: jnp.sum(l_sc.apply(p, x)[0] ** 2))(params)
    g_ei = jax.grad(lambda p: jnp.sum(l_ei.apply(p, x)[0] ** 2))(params)
    for k in g_sc:
        np.testing.assert_allclose(np.asarray(g_sc[k]), np.asarray(g_ei[k]),
                                   rtol=1e-4, atol=1e-4)

    # expert-parallel: both modes through the all_to_all path
    l_sc_ep = MoELayer(**kw, dispatch="scatter", expert_axis="model")
    l_ei_ep = MoELayer(**kw, dispatch="einsum", expert_axis="model")
    y_sc_ep, _ = moe_apply_sharded(l_sc_ep, mesh, params, x)
    y_ei_ep, _ = moe_apply_sharded(l_ei_ep, mesh, params, x)
    np.testing.assert_allclose(np.asarray(y_sc_ep), np.asarray(y_ei_ep),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # compile-bound (~15s); tier-1 870s cap
def test_top2_second_choice_queues_after_first(mesh):
    """Expert-parallel top-2 equals the dense-dispatch top-2 (the all_to_all path
    is routing-agnostic), and grads stay finite."""
    dense = MoELayer(H, F, E, capacity_factor=16.0, top_k=2)
    ep = MoELayer(H, F, E, capacity_factor=16.0, top_k=2, expert_axis="model")
    params = dense.init(jax.random.PRNGKey(13))
    x = jax.random.normal(jax.random.PRNGKey(14), (4, 8, H), jnp.float32)
    y_d, _ = dense.apply(params, x)
    y_p, _ = moe_apply_sharded(ep, mesh, params, x)
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_d), rtol=2e-5,
                               atol=2e-6)
    g = jax.grad(lambda p: jnp.sum(moe_apply_sharded(ep, mesh, p, x)[0] ** 2))(params)
    assert all(bool(jnp.isfinite(v).all()) for v in jax.tree_util.tree_leaves(g))


def test_top2_drop_priority_under_tight_capacity():
    """Under contention the SECOND choice drops, never the first (GShard's
    two-pass assignment): with capacity 1 per expert and crossed preferences,
    each token keeps exactly its first-choice contribution."""
    layer = MoELayer(4, 8, 2, capacity_factor=1e-9, top_k=2)  # capacity clamps to 1
    params = layer.init(jax.random.PRNGKey(15))
    # gate logits chosen so x0 -> top1 expert0 / top2 expert1, x1 -> the reverse
    gate = np.zeros((4, 2), np.float32)
    gate[0] = [3.0, 1.0]
    gate[1] = [1.0, 3.0]
    params = dict(params, gate_w=jnp.asarray(gate))
    x = jnp.asarray(np.eye(2, 4, dtype=np.float32))  # x0 = e0, x1 = e1
    y, _ = layer.apply(params, x)

    probs = np.asarray(jax.nn.softmax(jnp.asarray(np.asarray(x) @ gate), axis=-1))

    def expert_out(e, xn):
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            xn @ np.asarray(params["w_in"][e]) + np.asarray(params["b_in"][e]))))
        return h @ np.asarray(params["w_out"][e]) + np.asarray(params["b_out"][e])

    # each expert's single slot goes to its FIRST-choice token; the crossed
    # second choices (x0->e1, x1->e0) must both drop, leaving the normalized
    # first-choice contribution only
    for n, e1 in ((0, 0), (1, 1)):
        w1 = probs[n, e1] / (probs[n, 0] + probs[n, 1])
        np.testing.assert_allclose(np.asarray(y[n]),
                                   w1 * expert_out(e1, np.asarray(x[n])),
                                   rtol=1e-5, atol=1e-6)
