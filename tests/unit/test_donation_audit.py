"""Donation audit (VERDICT r4 #10): the 1.5B-scale engine programs must donate
cleanly — donation is the HBM margin that decides the remat policy.

Background: the suite's "Some donated buffers were not usable" warnings come
from paths that donate the GRAD tree into the update program. Grad leaves can
rarely alias an output (opt state is a flat fp32 shard; grads are per-leaf
model shapes), so XLA reports them unusable for output aliasing — but donation
still allows the buffers to be overwritten mid-execution, which is the point
(at 1.5B an undonated fp32 grad tree holds a full param-tree of HBM through
the update). Those warnings are expected and pinned here as grad-only.

What must be CLEAN is the fused single-jit step (the pinned 1.5B bench path):
it donates only opt_state, whose flat shard aliases the updated shard exactly.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from simple_model import SimpleModel, simple_config


def _shard_pair(n):
    """External-master (init, apply) client pair — the 1.5B bench's optimizer
    structure (bench.py _shard_optimizer) at test scale."""
    def init(params):
        flat = jnp.concatenate([p.reshape(-1).astype(jnp.float32)
                                for p in jax.tree_util.tree_leaves(params)])
        shard = flat[: flat.shape[0] // n]
        return {"master": shard, "m1": jnp.zeros_like(shard),
                "m2": jnp.zeros_like(shard)}

    def apply(grads, opt_state, master, step, hyper):
        g = jnp.concatenate([x.reshape(-1).astype(jnp.float32)
                             for x in jax.tree_util.tree_leaves(grads)])
        gs = g[: opt_state["master"].shape[0]]
        m1 = 0.9 * opt_state["m1"] + 0.1 * gs
        m2 = 0.999 * opt_state["m2"] + 0.001 * gs * gs
        new_master = opt_state["master"] - hyper["lr"] * m1 / (jnp.sqrt(m2) + 1e-8)
        return None, {"master": new_master, "m1": m1, "m2": m2}

    apply.external_master = True
    return init, apply


def _build(gas):
    model = SimpleModel(16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        optimizer=_shard_pair(4),
        config_params=simple_config(batch=8 * gas, gradient_accumulation_steps=gas,
                                    zero_optimization={"stage": 2},
                                    zero_allow_untested_optimizer=True))
    return engine


def _run_steps(engine, n=2):
    x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
    for _ in range(n):
        loss = engine(x, np.tanh(x))
        engine.backward(loss)
        engine.step()


def test_fused_step_donates_cleanly():
    """The external-master FUSED path (the pinned 1.5B bench structure: gas=1,
    client shard pair, ZeRO-2) must produce ZERO donation warnings: its only
    donated argument (opt_state) aliases the updated shard leaf-for-leaf."""
    engine = _build(gas=1)
    assert engine._run_fused_step is not None, "fused path did not engage"
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        _run_steps(engine)
    bad = [str(w.message) for w in caught if "donated" in str(w.message).lower()]
    assert not bad, f"fused step mis-donates: {bad}"


def test_unfused_accumulation_warning_is_grad_only():
    """The unfused external-master path donates the accumulated GRAD tree on
    purpose (mid-execution reuse). Pin that any 'not usable' warning lists only
    fp32 grad-shaped buffers — if an opt-state or scaler buffer ever shows up
    here, the update stopped aliasing and the 1.5B HBM margin silently shrank."""
    engine = _build(gas=2)
    assert engine._run_fused_step is None
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
        for _ in range(2):  # gas=2: two micro-steps per optimizer step
            loss = engine(x, np.tanh(x))
            engine.backward(loss)
        engine.step()
    msgs = [str(w.message) for w in caught if "donated" in str(w.message).lower()]
    for m in msgs:
        # grads are fp32 here (stage 2 keeps compute-dtype grads, fp32 under
        # fp32 compute); the flat opt shard is fp32[12] (196 params / 4 -> 49?)
        # — assert NO buffer matching the opt shard length appears
        assert "float32" in m, m
    shard_len = int(engine.opt_state["master"].shape[0])
    for m in msgs:
        assert f"float32[{shard_len}]" not in m, \
            f"opt-state shard appears in donation warning: {m}"
