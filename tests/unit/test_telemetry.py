"""Telemetry subsystem tests (docs/telemetry.md).

Covers the four pillars and their core guarantee: default-mode telemetry is
NON-PERTURBING — the compiled step program is instruction-identical with
telemetry on and off (named_scope is metadata; the watchdog's AOT cache runs
the same executable jit would), and the only per-step block rides the loss
fetch the engine already performs.
"""

import json
import glob
import logging
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import logger
from deepspeed_tpu.utils.hlo import (collective_counts, instruction_count,
                                     optimized_hlo)
from deepspeed_tpu.utils.telemetry import CompileWatchdog, TelemetrySession
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.records = []

    def emit(self, record):
        self.records.append(record)

    @property
    def text(self):
        return "\n".join(r.getMessage() for r in self.records)


@pytest.fixture
def capture():
    h = _Capture()
    logger.addHandler(h)
    try:
        yield h
    finally:
        logger.removeHandler(h)


def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))


def _run_steps(eng, steps, n=8):
    xs, ys = _batch(n)
    for _ in range(steps):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()


# --------------------------------------------------------------- pillar 1+4:
# non-perturbing step metrics + resource ledger through scalars.jsonl
def test_per_step_scalars_and_summary(tmp_path):
    eng = _build(telemetry={"enabled": True, "peak_tflops": 1e-6, "mfu_window": 4,
                            "output_path": str(tmp_path), "job_name": "tel"})
    _run_steps(eng, 4)
    eng.telemetry.close()
    path = os.path.join(str(tmp_path), "tel", "scalars.jsonl")
    scalars = [json.loads(l) for l in open(path)]
    tags = {s["tag"] for s in scalars}
    assert "Telemetry/Samples/step_time_ms" in tags
    assert "Telemetry/Samples/samples_per_sec" in tags
    assert "Telemetry/Samples/wire_bytes" in tags
    # rolling MFU needs >= 1 compile-free step; 4 steps with stable shapes give 3
    assert "Telemetry/Samples/mfu" in tags
    # HBM watermarks are emitted only where the backend reports memory_stats
    # (None on CPU CI) — when present they must be positive
    for s in scalars:
        if s["tag"].startswith("Telemetry/Samples/hbm_"):
            assert s["value"] > 0
    times = [s["value"] for s in scalars if s["tag"] == "Telemetry/Samples/step_time_ms"]
    assert len(times) == 4 and all(t > 0 for t in times)

    summary = eng.telemetry.summary()
    assert summary["steps_recorded"] == 4
    assert summary["compile_count"] >= 2  # loss_and_grad + apply_update at minimum
    assert summary["mfu"] is not None and summary["mfu"] > 0
    assert summary["compile_seconds"] > 0


def test_default_telemetry_blocks_are_only_the_loss_fetch(tmp_path):
    """wall_clock_breakdown=true is suppressed under telemetry (its section
    barriers perturb the run); perturbing_breakdown=true forces it with a loud
    one-time warning."""
    h = _Capture()
    logger.addHandler(h)
    try:
        eng = _build(wall_clock_breakdown=True,
                     telemetry={"enabled": True, "output_path": str(tmp_path)})
        assert eng.wall_clock_breakdown() is False
        assert "suppressed" in h.text
        h.records.clear()
        eng2 = _build(telemetry={"enabled": True, "perturbing_breakdown": True,
                                 "output_path": str(tmp_path)})
        assert eng2.wall_clock_breakdown() is True
        assert eng2.wall_clock_breakdown() is True
        warns = [r for r in h.records if "perturbing_breakdown" in r.getMessage()]
        assert len(warns) == 1, "loud warning must fire exactly once"
        # telemetry off: the plain config flag is untouched
        eng3 = _build(wall_clock_breakdown=True)
        assert eng3.wall_clock_breakdown() is True
    finally:
        logger.removeHandler(h)


# --------------------------------------------------------------- pillar 2:
# trace windows around the configured step range — one window shared with the
# profile-observatory readback assertions (docs/profile.md): trace start/stop
# late in a long pytest process is expensive, so the artifact-layout checks
# and the Profile/* ingest checks ride the SAME traced run
def test_trace_window_artifacts_and_profile_readback(tmp_path):
    trace_dir = os.path.join(str(tmp_path), "trace")
    eng = _build(telemetry={"enabled": True, "trace_steps": [1, 2],
                            "trace_dir": trace_dir, "peak_tflops": 1e-6,
                            "profile": {"enabled": True},
                            "output_path": str(tmp_path), "job_name": "prof"})
    assert eng.telemetry.profile_enabled
    assert eng.telemetry.watchdog.profile_scopes
    xs, ys = _batch()
    # step 0: before the window — the trace dir must not even exist yet
    loss = eng(xs, ys); eng.backward(loss); eng.step()
    if eng.telemetry._trace_failed:
        pytest.skip("profiler backend unavailable on this platform")
    assert not os.path.exists(trace_dir)
    # step 1: inside the window (started at its first forward)
    loss = eng(xs, ys); eng.backward(loss); eng.step()
    if eng.telemetry._trace_failed:
        pytest.skip("profiler backend unavailable on this platform")
    # step 2: past the window — must already be stopped and written
    loss = eng(xs, ys); eng.backward(loss); eng.step()
    assert eng.telemetry._trace_done and not eng.telemetry._trace_active
    # the profiler session lands in the run/host-namespaced subdir
    from deepspeed_tpu.utils.profile_ingest import (find_trace_files,
                                                    scan_trace_dirs)
    runs = scan_trace_dirs(trace_dir)
    assert [(d["run"], d["host"]) for d in runs] == \
        [(eng.telemetry.run_id, eng.telemetry.host_id)]
    assert runs[0]["path"] == eng.telemetry.trace_output_dir
    assert find_trace_files(runs[0]["path"]), \
        f"no profiler artifacts under {runs[0]['path']}"
    # profile observatory: the window was read back at close
    prof = eng.telemetry.last_profile
    assert prof is not None, "window closed but no profile was ingested"
    assert prof["total_slices"] > 0
    assert prof["classes"]["compute"]["busy_us"] > 0
    # the compile-time catalog joined: the step program is attributed (the
    # module name varies by engine path — jit_loss_and_grad vs the ZeRO
    # jit_local_loss_and_grad — so key on the joined watchdog program)
    joined = {v.get("program") for v in prof["programs"].values()}
    assert "loss_and_grad" in joined and "apply_update" in joined
    eng.telemetry.close()
    scalars = [json.loads(l) for l in
               open(os.path.join(str(tmp_path), "prof", "scalars.jsonl"))]
    tags = {s["tag"] for s in scalars}
    for tag in ("Profile/compute_ms", "Profile/collective_ici_ms",
                "Profile/collective_dcn_ms", "Profile/host_gap_ms",
                "Profile/step_wall_ms", "Profile/exposed_ici_ms",
                "Profile/exposed_dcn_ms"):
        assert tag in tags, f"missing {tag}"
    # summary carries the condensed per-step decomposition
    summary = eng.telemetry.summary()
    assert summary["profile"] is not None
    assert summary["profile"]["step_wall_ms"] > 0
    assert summary["trace"]["done"] is True
    # and the flight-recorder embedding sees the same report
    snap = eng.telemetry.profile_snapshot()
    assert snap["report"] is prof and snap["trace_failed"] is False


def test_trace_dir_namespacing_and_legacy_layout(tmp_path):
    """Two sessions sharing one trace_dir get distinct trace_<run>_host<h>/
    subdirs (the PR-14 flight-recorder naming); run_id=\"\" opts back into the
    legacy layout where the profiler writes into trace_dir itself."""
    shared = str(tmp_path / "shared")
    s1 = TelemetrySession(trace_dir=shared, trace_steps=[0, 1],
                          run_id="run-a", host_id=0, output_path=str(tmp_path))
    s2 = TelemetrySession(trace_dir=shared, trace_steps=[0, 1],
                          run_id="run-b", host_id=1, output_path=str(tmp_path))
    assert s1.trace_output_dir == os.path.join(shared, "trace_run-a_host0")
    assert s2.trace_output_dir == os.path.join(shared, "trace_run-b_host1")
    assert s1.trace_output_dir != s2.trace_output_dir
    legacy = TelemetrySession(trace_dir=shared, trace_steps=[0, 1],
                              run_id="", output_path=str(tmp_path))
    assert legacy.trace_output_dir == shared
    for s in (s1, s2, legacy):
        s.close()


def test_trace_failure_latched_into_summary(tmp_path, capture):
    """A profiler that cannot start warns ONCE, latches _trace_failed, stops
    all window bookkeeping, and surfaces the flag in summary()['trace'] so a
    bench run can't silently lose its measurement."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("a file where the trace dir must go")
    session = TelemetrySession(trace_dir=str(blocker), trace_steps=[0, 2],
                               run_id="", output_path=str(tmp_path))
    session.on_step_begin(0)
    assert session._trace_failed and not session._trace_active
    assert capture.text.count("profiler trace unavailable") == 1
    # subsequent steps must not retry or warn again
    session.on_step_begin(1)
    session.end_step(1, 8)
    assert capture.text.count("profiler trace unavailable") == 1
    summary = session.summary()
    assert summary["trace"]["failed"] is True
    assert summary["trace"]["done"] is False
    session.close()


def test_trace_steps_validation():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    for bad in ([3], [5, 2], [2, 2], [-1, 4], "0:2", [0, 2, 4]):
        with pytest.raises(ValueError):
            DeepSpeedConfig({"train_batch_size": 8,
                             "telemetry": {"enabled": True, "trace_steps": bad}},
                            world_size=1)
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "telemetry": {"enabled": True, "trace_steps": [2, 5]}},
                          world_size=1)
    assert cfg.telemetry_trace_steps == (2, 5)


# --------------------------------------------------------------- pillar 3:
# compile watchdog — observed compiles, shape-driven recompiles, storm warning
def test_watchdog_counts_shape_driven_recompile(capture, tmp_path):
    eng = _build(telemetry={"enabled": True, "recompile_warn": 2,
                            "output_path": str(tmp_path)})
    _run_steps(eng, 2, n=8)
    base = eng.telemetry.watchdog.compiles("loss_and_grad")
    assert base >= 1
    # a different leading batch dim reaches the jitted step: the classic silent
    # recompile. 16 stays divisible by the 8-device data axis.
    _run_steps(eng, 1, n=16)
    wd = eng.telemetry.watchdog
    assert wd.compiles("loss_and_grad") == base + 1
    assert wd.recompiles("loss_and_grad") >= 1
    assert len(wd.records["loss_and_grad"]) >= 2  # distinct signatures
    assert "recompile storm" in capture.text
    assert "loss_and_grad" in capture.text
    # compile records carry the cost/memory analysis of each compile
    rec = next(iter(wd.records["loss_and_grad"].values()))
    assert rec.compile_seconds > 0
    assert eng.telemetry.summary()["recompile_count"] >= 1


def test_watchdog_storm_warning_threshold():
    wd = CompileWatchdog(recompile_warn=3)
    h = _Capture()
    logger.addHandler(h)
    try:
        wd.record("prog", ("sig_a",), 0.1)
        wd.record("prog", ("sig_b",), 0.1)
        assert "recompile storm" not in h.text
        wd.record("prog", ("sig_c",), 0.1)
        assert "recompile storm" in h.text
        n_warn = h.text.count("recompile storm")
        wd.record("prog", ("sig_d",), 0.1)  # storm warns once per program
        assert h.text.count("recompile storm") == n_warn
    finally:
        logger.removeHandler(h)
    assert wd.compiles("prog") == 4
    assert wd.recompiles("prog") == 3
    assert wd.compile_seconds("prog") == pytest.approx(0.4)


# --------------------------------------------------------------- the core
# guarantee: default telemetry adds ZERO HLO instructions to the step program
def test_default_telemetry_is_hlo_identical(tmp_path):
    eng_off = _build()
    eng_on = _build(telemetry={"enabled": True, "output_path": str(tmp_path)})
    xs, ys = _batch()
    hlos = []
    for eng in (eng_off, eng_on):
        jitted = eng._jit_loss_and_grad  # raw jit vs _WatchedJit proxy
        hlos.append(optimized_hlo(jitted, eng.params,
                                  eng.scaler_state.cur_scale, xs, ys))
    assert instruction_count(hlos[0]) > 0
    assert instruction_count(hlos[0]) == instruction_count(hlos[1])
    assert collective_counts(hlos[0]) == collective_counts(hlos[1])


def test_instruction_count_parses_hlo():
    hlo = """HloModule m

%fused_add (p0: f32[8], p1: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %p1 = f32[8]{0} parameter(1)
  ROOT %add.1 = f32[8]{0} add(%p0, %p1)
}

ENTRY %main (a: f32[8], b: f32[8]) -> f32[8] {
  %a = f32[8]{0} parameter(0)
  %b = f32[8]{0} parameter(1)
  ROOT %fusion = f32[8]{0} fusion(%a, %b), kind=kLoop, calls=%fused_add
}
"""
    assert instruction_count(hlo) == 6


# --------------------------------------------------------------- results parity:
# the watchdog's AOT execution path must be bit-identical to the raw jit path
def test_watched_step_matches_unwatched(tmp_path):
    eng_off = _build()
    eng_on = _build(telemetry={"enabled": True, "output_path": str(tmp_path)})
    xs, ys = _batch()
    for step in range(3):
        l_off = eng_off(xs, ys); eng_off.backward(l_off); eng_off.step()
        l_on = eng_on(xs, ys); eng_on.backward(l_on); eng_on.step()
        assert float(jax.device_get(l_off)) == float(jax.device_get(l_on)), step
    p_off = jax.device_get(eng_off.params)
    p_on = jax.device_get(eng_on.params)
    for a, b in zip(jax.tree_util.tree_leaves(p_off), jax.tree_util.tree_leaves(p_on)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_session_uses_engine_monitor_when_tensorboard_enabled(tmp_path):
    eng = _build(tensorboard={"enabled": True, "output_path": str(tmp_path),
                              "job_name": "tb"},
                 telemetry={"enabled": True})
    assert eng.telemetry.monitor is eng.monitor
    _run_steps(eng, 2)
    eng.monitor.close()
    scalars = [json.loads(l) for l in
               open(os.path.join(str(tmp_path), "tb", "scalars.jsonl"))]
    tags = {s["tag"] for s in scalars}
    # engine training scalars and telemetry scalars share the sink
    assert "Train/Samples/train_loss" in tags
    assert "Telemetry/Samples/step_time_ms" in tags


# --------------------------------------------------------------- profile
# observatory (docs/profile.md): the ingest/scalars assertions ride the
# trace window in test_trace_window_artifacts_and_profile_readback above;
# here: the zero-instruction guarantee every observatory pins
def test_profile_enabled_is_hlo_identical(tmp_path):
    """telemetry.profile reads trace files back on the host — the lowered
    step program must be instruction-identical with the block on or off."""
    eng_off = _build(telemetry={"enabled": True,
                                "output_path": str(tmp_path)})
    eng_on = _build(telemetry={"enabled": True, "trace_steps": [1, 2],
                               "trace_dir": os.path.join(str(tmp_path), "tr"),
                               "profile": {"enabled": True},
                               "output_path": str(tmp_path)})
    xs, ys = _batch()
    hlos = []
    for eng in (eng_off, eng_on):
        hlos.append(optimized_hlo(eng._jit_loss_and_grad, eng.params,
                                  eng.scaler_state.cur_scale, xs, ys))
    assert instruction_count(hlos[0]) > 0
    assert instruction_count(hlos[0]) == instruction_count(hlos[1])
    assert collective_counts(hlos[0]) == collective_counts(hlos[1])
