"""Config system tests (parity with reference tests/unit/test_config.py semantics)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig


def base_dict(**over):
    d = {"train_batch_size": 8, "optimizer": {"type": "adam", "params": {"lr": 1e-3}}}
    d.update(over)
    return d


def test_batch_all_given():
    cfg = DeepSpeedConfig(base_dict(train_batch_size=32, train_micro_batch_size_per_gpu=4,
                                    gradient_accumulation_steps=2), world_size=4)
    assert cfg.train_batch_size == 32
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 2


def test_batch_infer_grad_acc():
    cfg = DeepSpeedConfig(base_dict(train_batch_size=32, train_micro_batch_size_per_gpu=4), world_size=4)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_infer_micro():
    cfg = DeepSpeedConfig(base_dict(train_batch_size=32, gradient_accumulation_steps=2), world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_infer_train_batch():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 2}, world_size=4)
    assert cfg.train_batch_size == 32


def test_batch_only_train_batch():
    cfg = DeepSpeedConfig(base_dict(train_batch_size=32), world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 8
    assert cfg.gradient_accumulation_steps == 1


def test_batch_only_micro():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4}, world_size=4)
    assert cfg.train_batch_size == 16
    assert cfg.gradient_accumulation_steps == 1


def test_batch_nothing_raises():
    with pytest.raises(AssertionError):
        DeepSpeedConfig({"gradient_accumulation_steps": 2}, world_size=4)


def test_batch_mismatch_raises():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(base_dict(train_batch_size=32, train_micro_batch_size_per_gpu=5,
                                  gradient_accumulation_steps=2), world_size=4)


def test_duplicate_key_rejected(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p), world_size=1)


def test_json_file_load(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(base_dict()))
    cfg = DeepSpeedConfig(str(p), world_size=1)
    assert cfg.train_batch_size == 8
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 1e-3


def test_zero_config():
    cfg = DeepSpeedConfig(base_dict(fp16={"enabled": True},
                                    zero_optimization={"stage": 2, "cpu_offload": True,
                                                       "reduce_bucket_size": 1000}), world_size=1)
    assert cfg.zero_enabled
    assert cfg.zero_optimization_stage == 2
    assert cfg.zero_config.cpu_offload
    assert cfg.zero_config.reduce_bucket_size == 1000


def test_zero_requires_mixed_precision_ok_with_bf16_default():
    cfg = DeepSpeedConfig(base_dict(zero_optimization={"stage": 1}), world_size=1)
    assert cfg.zero_enabled and cfg.bf16_enabled


def test_zero_stage_bounds():
    # stage 3 (parameter sharding) is supported — beyond the v0.3.0 reference;
    # stage 4 does not exist
    cfg = DeepSpeedConfig(base_dict(zero_optimization={"stage": 3}), world_size=1)
    assert cfg.zero_optimization_stage == 3
    with pytest.raises(AssertionError):
        DeepSpeedConfig(base_dict(zero_optimization={"stage": 4}), world_size=1)


def test_cpu_offload_requires_stage2():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(base_dict(zero_optimization={"stage": 1, "cpu_offload": True}), world_size=1)


def test_fp16_loss_scale_knobs():
    cfg = DeepSpeedConfig(base_dict(fp16={"enabled": True, "loss_scale": 0, "initial_scale_power": 16,
                                          "loss_scale_window": 500, "hysteresis": 4, "min_loss_scale": 2}),
                          world_size=1)
    assert cfg.fp16_enabled
    assert not cfg.bf16_enabled
    assert cfg.loss_scale == 0
    assert cfg.initial_scale_power == 16
    assert cfg.loss_scale_window == 500
    assert cfg.hysteresis == 4
    assert cfg.min_loss_scale == 2


def test_scheduler_block():
    cfg = DeepSpeedConfig(base_dict(scheduler={"type": "WarmupLR",
                                               "params": {"warmup_num_steps": 10}}), world_size=1)
    assert cfg.scheduler_name == "WarmupLR"
    assert cfg.scheduler_params["warmup_num_steps"] == 10


def test_sparse_attention_block():
    cfg = DeepSpeedConfig(base_dict(sparse_attention={"mode": "fixed", "block": 16,
                                                      "num_local_blocks": 4}), world_size=1)
    assert cfg.sparse_attention.mode == "fixed"
    assert cfg.sparse_attention.block == 16
    assert cfg.sparse_attention.num_local_blocks == 4


def test_compilation_cache_dir_config(tmp_path):
    """compilation_cache_dir flows from JSON to jax.config at engine construction."""
    import jax
    import deepspeed_tpu
    from simple_model import SimpleModel, simple_config

    cache = str(tmp_path / "xla_cache")
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        model = SimpleModel(16)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
            config_params=simple_config(compilation_cache_dir=cache))
        assert engine.config.compilation_cache_dir == cache
        assert jax.config.jax_compilation_cache_dir == cache
    finally:
        # process-global jax config: restore so later tests don't inherit a
        # cache pointed at this test's (soon-deleted) tmp dir
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def _capture_warnings(monkeypatch):
    from deepspeed_tpu.utils import logger
    msgs = []
    monkeypatch.setattr(logger, "warning", lambda m, *a: msgs.append(m % a if a else m))
    return msgs


def test_offload_optimizer_block_parses_and_implies_offload():
    cfg = DeepSpeedConfig(base_dict(zero_optimization={
        "stage": 2, "offload_optimizer": {"device": "cpu", "pipeline": True,
                                          "pipeline_depth": 3,
                                          "max_region_elements": 1 << 22}}), world_size=1)
    zc = cfg.zero_config
    assert zc.cpu_offload  # the block implies the legacy enable switch
    assert zc.offload_device == "cpu"
    assert zc.offload_pipeline is True
    assert zc.offload_pipeline_depth == 3
    assert zc.offload_max_region_elements == 1 << 22


def test_offload_optimizer_defaults():
    cfg = DeepSpeedConfig(base_dict(zero_optimization={"stage": 2, "cpu_offload": True}),
                          world_size=1)
    zc = cfg.zero_config
    assert zc.offload_device == "cpu"
    assert zc.offload_pipeline is True
    assert zc.offload_pipeline_depth == 2
    assert zc.offload_max_region_elements == "auto"


def test_offload_optimizer_explicit_disable_wins(monkeypatch):
    msgs = _capture_warnings(monkeypatch)
    cfg = DeepSpeedConfig(base_dict(zero_optimization={
        "stage": 2, "cpu_offload": False, "offload_optimizer": {"pipeline_depth": 4}}),
        world_size=1)
    assert cfg.zero_config.cpu_offload is False  # the explicit boolean wins
    assert cfg.zero_config.offload_pipeline_depth == 4
    assert any("explicitly" in m and "DISABLED" in m for m in msgs), msgs


def test_offload_optimizer_validation():
    with pytest.raises(ValueError, match="must be a dict"):
        DeepSpeedConfig(base_dict(zero_optimization={"stage": 2,
                                                     "offload_optimizer": "cpu"}),
                        world_size=1)
    with pytest.raises(ValueError, match="not supported"):
        DeepSpeedConfig(base_dict(zero_optimization={
            "stage": 2, "offload_optimizer": {"device": "nvme"}}), world_size=1)
    with pytest.raises(ValueError, match="pipeline_depth"):
        DeepSpeedConfig(base_dict(zero_optimization={
            "stage": 2, "offload_optimizer": {"pipeline_depth": 0}}), world_size=1)
    with pytest.raises(ValueError, match="max_region_elements"):
        DeepSpeedConfig(base_dict(zero_optimization={
            "stage": 2, "offload_optimizer": {"max_region_elements": -1}}), world_size=1)


def test_offload_optimizer_unknown_key_warns(monkeypatch):
    msgs = _capture_warnings(monkeypatch)
    DeepSpeedConfig(base_dict(zero_optimization={
        "stage": 2, "offload_optimizer": {"buffer_count": 4}}), world_size=1)
    assert any("unknown" in m and "buffer_count" in m for m in msgs), msgs


def test_comm_dtype_conflict_warns(monkeypatch):
    """allreduce_always_fp32 + a conflicting communication_data_type must warn and
    name the winner (the explicit dtype — engine.py applies it last)."""
    msgs = _capture_warnings(monkeypatch)
    DeepSpeedConfig(base_dict(bf16={"enabled": True}, allreduce_always_fp32=True,
                              communication_data_type="bf16"), world_size=1)
    assert any("communication_data_type wins" in m and "bf16" in m for m in msgs), msgs

    msgs.clear()
    # agreeing settings (fp32 + fp32) stay silent
    DeepSpeedConfig(base_dict(bf16={"enabled": True}, allreduce_always_fp32=True,
                              communication_data_type="fp32"), world_size=1)
    assert not any("communication_data_type wins" in m for m in msgs), msgs
