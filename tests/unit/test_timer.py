"""Timer tests (reference utils/timer.py: SynchronizedWallClockTimer l.20,
ThroughputTimer l.100)."""

import logging
import time

import pytest

from deepspeed_tpu.utils import timer as timer_mod
from deepspeed_tpu.utils import logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer


def test_wallclock_timer_accumulates_and_resets():
    timers = SynchronizedWallClockTimer(sync_fn=lambda: None)
    t = timers("fwd")
    t.start(); time.sleep(0.02); t.stop()
    e1 = t.elapsed(reset=False)
    assert e1 >= 0.015
    t.start(); time.sleep(0.02); t.stop()
    assert t.elapsed(reset=False) > e1, "stop() must accumulate across windows"
    t.reset()
    assert t.elapsed(reset=False) == 0.0
    # same name returns the same timer object
    assert timers("fwd") is t


def test_wallclock_timer_log_runs(caplog):
    timers = SynchronizedWallClockTimer(sync_fn=lambda: None)
    timers("a").start(); timers("a").stop()
    timers("b").start(); timers("b").stop()
    timers.log(["a", "b"])          # must not raise; resets by default
    assert timers("a").elapsed(reset=False) == 0.0


def test_default_sync_failure_warns_once(monkeypatch):
    """Regression: a failed effects_barrier was swallowed silently, so timers
    quietly measured dispatch instead of device compute. The first failure must
    warn through the package logger (once — not per timer boundary)."""
    import jax

    class _Capture(logging.Handler):
        def __init__(self):
            super().__init__(level=logging.DEBUG)
            self.records = []

        def emit(self, record):
            self.records.append(record)

    def boom():
        raise RuntimeError("barrier exploded")

    monkeypatch.setattr(jax, "effects_barrier", boom)
    monkeypatch.setattr(timer_mod, "_sync_failure_warned", False)
    h = _Capture()
    logger.addHandler(h)
    try:
        timer_mod._default_sync()  # must not raise
        timer_mod._default_sync()
    finally:
        logger.removeHandler(h)
    warnings = [r for r in h.records if r.levelno >= logging.WARNING
                and "timer sync failed" in r.getMessage()]
    assert len(warnings) == 1, [r.getMessage() for r in h.records]
    assert "DISPATCH" in warnings[0].getMessage()


def test_throughput_timer_reports_samples_per_sec():
    tt = ThroughputTimer(batch_size=8, num_workers=1, start_step=1, steps_per_output=None)
    for _ in range(4):
        tt.start(); time.sleep(0.005); tt.stop(report_speed=False)
    sps = tt.avg_samples_per_sec()
    assert 0 < sps < 8 / 0.005 * 2, sps
