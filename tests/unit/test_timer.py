"""Timer tests (reference utils/timer.py: SynchronizedWallClockTimer l.20,
ThroughputTimer l.100)."""

import time

from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer


def test_wallclock_timer_accumulates_and_resets():
    timers = SynchronizedWallClockTimer(sync_fn=lambda: None)
    t = timers("fwd")
    t.start(); time.sleep(0.02); t.stop()
    e1 = t.elapsed(reset=False)
    assert e1 >= 0.015
    t.start(); time.sleep(0.02); t.stop()
    assert t.elapsed(reset=False) > e1, "stop() must accumulate across windows"
    t.reset()
    assert t.elapsed(reset=False) == 0.0
    # same name returns the same timer object
    assert timers("fwd") is t


def test_wallclock_timer_log_runs(caplog):
    timers = SynchronizedWallClockTimer(sync_fn=lambda: None)
    timers("a").start(); timers("a").stop()
    timers("b").start(); timers("b").stop()
    timers.log(["a", "b"])          # must not raise; resets by default
    assert timers("a").elapsed(reset=False) == 0.0


def test_throughput_timer_reports_samples_per_sec():
    tt = ThroughputTimer(batch_size=8, num_workers=1, start_step=1, steps_per_output=None)
    for _ in range(4):
        tt.start(); time.sleep(0.005); tt.stop(report_speed=False)
    sps = tt.avg_samples_per_sec()
    assert 0 < sps < 8 / 0.005 * 2, sps
