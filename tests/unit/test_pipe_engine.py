"""Pipeline engine end-to-end tests: LinearStack pipe vs sequential parity, tied weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.pipe import LayerSpec, TiedLayerSpec, PipelineModule
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine, PipelineError
from oldjax import grad_through_shard_map_xfail

HIDDEN = 8


class Linear:
    """Minimal pure-function layer module: init(rng, x) -> params; apply(params, x)."""

    def __init__(self, dim, activation=True):
        self.dim = dim
        self.activation = activation

    def init(self, rng, x):
        k1, _ = jax.random.split(rng)
        return {"w": jax.random.normal(k1, (x.shape[-1], self.dim), jnp.float32) * 0.3,
                "b": jnp.zeros((self.dim,), jnp.float32)}

    def apply(self, params, x):
        y = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
        return jnp.tanh(y) if self.activation else y

    def param_shapes(self):
        return [(HIDDEN, self.dim), (self.dim,)]


def mse_loss(out, target):
    return jnp.mean(jnp.square(out.astype(jnp.float32) - target.astype(jnp.float32)))


def make_pipe(num_layers=4, num_stages=2, seed=0, tied=False):
    if tied:
        layers = [TiedLayerSpec("emb", Linear, HIDDEN)] + \
                 [LayerSpec(Linear, HIDDEN) for _ in range(num_layers - 2)] + \
                 [TiedLayerSpec("emb", Linear, HIDDEN)]
    else:
        layers = [LayerSpec(Linear, HIDDEN) for _ in range(num_layers)]
    module = PipelineModule(layers=layers, num_stages=num_stages, loss_fn=mse_loss)
    sample = jnp.zeros((4, HIDDEN), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(seed), sample)
    return module, params


def pipe_config(batch=32, micro=2):
    # dp world is 8 virtual devices: batch 32 / (micro-batches 2 * dp 8) = micro size 2
    return {
        "train_batch_size": batch,
        "gradient_accumulation_steps": micro,
        "steps_per_print": 100,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }


def data_iter(hidden=HIDDEN, batch=4, seed=0):
    rng = np.random.default_rng(seed)
    w_true = np.random.default_rng(77).normal(size=(hidden, hidden)).astype(np.float32) * 0.4
    while True:
        x = rng.normal(size=(batch, hidden)).astype(np.float32)
        yield x, np.tanh(x @ w_true)


@pytest.mark.parametrize("num_stages", [
    1,
    pytest.param(2, marks=grad_through_shard_map_xfail),
    pytest.param(4, marks=grad_through_shard_map_xfail),
])
def test_pipe_training_loss_decreases(num_stages):
    module, params = make_pipe(num_layers=4, num_stages=num_stages)
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=pipe_config())
    assert isinstance(engine, PipelineEngine)
    it = data_iter(batch=16)
    losses = [float(jax.device_get(engine.train_batch(it))) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.8, f"{losses[0]} -> {losses[-1]}"


@grad_through_shard_map_xfail
def test_pipe_matches_sequential():
    """The same layers trained with 2 pipeline stages (SPMD executor) vs 1 stage give
    identical weights at fp32 — compared in the canonical layer-keyed representation.
    (fp32 pinned: cross-executor comparisons at bf16 drift through Adam's sqrt(v)
    normalization within a few steps.)"""
    results = []
    for stages in [1, 2]:
        module, params = make_pipe(num_layers=4, num_stages=stages, seed=5)
        cfg = pipe_config()
        cfg["bf16"] = {"enabled": False}
        engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                                   config_params=cfg)
        assert engine._spmd == (stages == 2), "2-stage homogeneous stack must route SPMD"
        it = data_iter(batch=16, seed=11)
        for _ in range(3):
            engine.train_batch(it)
        results.append({k: np.asarray(jax.device_get(v), np.float32)
                        for k, v in jax.tree_util.tree_flatten_with_path(
                            engine.canonical_master_params())[0]
                        for k, v in [("/".join(str(p) for p in k), v)]})
    for k in results[0]:
        np.testing.assert_allclose(results[0][k], results[1][k], rtol=1e-4, atol=1e-6,
                                   err_msg=f"mismatch in {k}")


@grad_through_shard_map_xfail
def test_spmd_loss_matches_instruction_executor_fp32():
    """VERDICT r3 #1 acceptance: under the SAME public API and config, the SPMD
    executor's per-step losses equal the instruction executor's at fp32."""
    losses = {}
    for mode in ["spmd", "instruction"]:
        module, params = make_pipe(num_layers=4, num_stages=2, seed=7)
        cfg = pipe_config()
        cfg["bf16"] = {"enabled": False}
        cfg["pipeline"] = {"spmd": mode == "spmd"}
        engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                                   config_params=cfg)
        assert engine._spmd == (mode == "spmd")
        it = data_iter(batch=16, seed=23)
        losses[mode] = [float(jax.device_get(engine.train_batch(it)))
                        for _ in range(4)]
    np.testing.assert_allclose(losses["spmd"], losses["instruction"], rtol=1e-6,
                               err_msg=f"{losses}")


@grad_through_shard_map_xfail
def test_pipe_tied_weights():
    module, params = make_pipe(num_layers=4, num_stages=2, tied=True)
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=pipe_config())
    assert "tied::emb" in engine.master_params
    it = data_iter(batch=16)
    for _ in range(5):
        loss = engine.train_batch(it)
    assert np.isfinite(float(jax.device_get(loss)))
    # only one copy of the tied params exists
    n_tied = sum(1 for k in engine.master_params if k.startswith("tied::"))
    assert n_tied == 1


def test_pipe_blocks_base_api():
    module, params = make_pipe()
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=pipe_config())
    with pytest.raises(PipelineError):
        engine.forward(np.zeros((4, HIDDEN)))
    with pytest.raises(PipelineError):
        engine.backward(None)
    with pytest.raises(PipelineError):
        engine.step()


def test_pipe_eval_batch():
    module, params = make_pipe()
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=pipe_config())
    loss = engine.eval_batch(data_iter(batch=16))
    assert np.isfinite(float(jax.device_get(loss)))


def test_partition_balanced_by_parameters():
    module, _ = make_pipe(num_layers=4, num_stages=2)
    # 4 equal layers over 2 stages -> 2+2 split
    assert module.parts == [0, 2, 4]


@grad_through_shard_map_xfail
def test_pipe_deep_schedule_many_microbatches():
    """4 stages x 8 micro-batches: stages have UNEQUAL buffer ring sizes, exercising the
    micro-batch-keyed channels (regression: receiver-local buffer ids don't align)."""
    module, params = make_pipe(num_layers=8, num_stages=4)
    cfg = {
        "train_batch_size": 64,  # 8 micro-batches x micro size 1 x dp 8
        "gradient_accumulation_steps": 8,
        "steps_per_print": 100,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=cfg)
    it = data_iter(batch=8)
    losses = [float(jax.device_get(engine.train_batch(it))) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


@grad_through_shard_map_xfail
def test_pipe_activation_checkpoint_interval():
    """activation_checkpoint_interval remats chunks of stage layers and must be a
    pure memory/compute tradeoff — identical training results."""
    results = []
    for interval in [0, 1, 2]:
        layers = [LayerSpec(Linear, HIDDEN) for _ in range(4)]
        module = PipelineModule(layers=layers, num_stages=2, loss_fn=mse_loss,
                                activation_checkpoint_interval=interval)
        sample = jnp.zeros((4, HIDDEN), jnp.float32)
        params = module.init_params(jax.random.PRNGKey(3), sample)
        engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                                   config_params=pipe_config())
        it = data_iter(batch=16, seed=13)
        for _ in range(3):
            engine.train_batch(it)
        results.append(jax.device_get(engine.master_params))
    for other in results[1:]:
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                    rtol=1e-5, atol=1e-6),
            results[0], other)


def test_pipe_eval_batch_inference_schedule_parity():
    """eval_batch executes the InferenceSchedule stream; its aggregate loss must equal
    the sequential whole-model loss over the same micro-batches."""
    module, params = make_pipe(num_layers=4, num_stages=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=pipe_config())
    it = data_iter(batch=16, seed=21)   # distinct micro-batches so mb routing matters
    batches = [next(it) for _ in range(engine.micro_batches)]
    got = float(jax.device_get(engine.eval_batch(iter(batches))))
    want = np.mean([float(jax.device_get(
        engine._whole_model_fn(engine.params, jnp.asarray(x), jnp.asarray(y))))
        for x, y in batches])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@grad_through_shard_map_xfail
def test_pipe_fp16_loss_scale_parity():
    """fp16 pipeline grads are loss-scaled in the stage backward and unscaled in the
    update: the first-step weights must match an fp32 run to fp16 resolution."""
    results = {}
    for prec in ["fp32", "fp16"]:
        module, params = make_pipe(num_layers=4, num_stages=2, seed=9)
        cfg = pipe_config()
        if prec == "fp16":
            cfg["fp16"] = {"enabled": True, "loss_scale": 1024.0}
        engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                                   config_params=cfg)
        it = data_iter(batch=16, seed=13)
        for _ in range(2):
            loss = engine.train_batch(it)
        results[prec] = (float(jax.device_get(loss)),
                         jax.device_get(engine.master_params))
    np.testing.assert_allclose(results["fp16"][0], results["fp32"][0], rtol=2e-2)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=3e-2, atol=2e-3),
        results["fp16"][1], results["fp32"][1])


@grad_through_shard_map_xfail
def test_pipe_fp16_overflow_skips_step():
    module, params = make_pipe(num_layers=4, num_stages=2)
    cfg = pipe_config()
    cfg["fp16"] = {"enabled": True, "loss_scale": 0, "initial_scale_power": 4,
                   "hysteresis": 1}
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=cfg)
    s0 = float(engine.loss_scale())
    before = jax.device_get(engine.master_params)

    def bad_iter():
        while True:
            yield (np.ones((16, HIDDEN), np.float32),
                   np.full((16, HIDDEN), 1e30, np.float32))  # cotangents overflow fp16

    engine.train_batch(bad_iter())
    assert engine.skipped_steps == 1
    assert float(engine.loss_scale()) == s0 / 2
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a, b),
                           jax.device_get(engine.master_params), before)


def test_pipe_wall_clock_breakdown_timers():
    module, params = make_pipe(num_layers=4, num_stages=2)
    cfg = pipe_config()
    cfg["wall_clock_breakdown"] = True
    cfg["pipeline"] = {"spmd": False}  # per-instruction timers are instruction-mode
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=cfg)
    engine.train_batch(data_iter(batch=16))
    for name in ["batch_input", "forward_microstep", "backward_microstep",
                 "pipe_send_output", "pipe_recv_input", "pipe_send_grad",
                 "pipe_recv_grad", "step_microstep", "train_batch"]:
        assert name in engine.timers.timers, f"missing timer {name}"
        assert engine.timers.timers[name].elapsed_ > 0 or name in (
            "pipe_send_output", "pipe_recv_input", "pipe_send_grad", "pipe_recv_grad")


def test_instruction_path_buffer_bound_m_much_greater_than_s():
    """The reference's num_pipe_buffers memory contract as a tested invariant
    (VERDICT r2 next #10): with M >> S the channel dicts must never hold more
    in-flight payloads than the receiver's ring size — the engine asserts this on
    every Send, so a clean train_batch at M = 8S IS the proof."""
    S, M = 2, 16
    module, params = make_pipe(num_layers=4, num_stages=S)
    cfg = pipe_config(batch=M * 8, micro=M)  # micro size 1 x dp 8
    cfg["pipeline"] = {"spmd": False}  # the buffer-ring contract is instruction-mode
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params, config_params=cfg)
    assert not engine._spmd
    assert engine.micro_batches == M
    it = data_iter(batch=8)
    losses = [float(jax.device_get(engine.train_batch(it))) for _ in range(2)]
    assert np.isfinite(losses).all()


@grad_through_shard_map_xfail
def test_spmd_pipe_composes_with_zero2():
    """Public-API pipeline + ZeRO-2: merge_zero_into claims a free data-divisible
    axis on the pipe-stacked master/optimizer state, so 2-D (pipe x data) state
    sharding happens under deepspeed.initialize with a JSON config."""
    hidden = 64  # [2, 64, 64] stacked weights: above min_size, 64 % dp(4) == 0
    layers = [LayerSpec(Linear, hidden) for _ in range(4)]
    module = PipelineModule(layers=layers, num_stages=2, loss_fn=mse_loss)
    params = module.init_params(jax.random.PRNGKey(3),
                                jnp.zeros((4, hidden), jnp.float32))
    cfg = pipe_config()
    cfg["zero_optimization"] = {"stage": 2}
    engine, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                               config_params=cfg)
    assert engine._spmd
    from deepspeed_tpu.runtime.pipe.engine import STACKED_KEY
    # stacked core master WEIGHTS are sharded on BOTH pipe (leading) and data axes
    w = engine.master_params[STACKED_KEY][0]["w"]
    spec = w.sharding.spec
    flat = [ax for e in spec if e for ax in ((e,) if isinstance(e, str) else e)]
    assert "pipe" in flat, spec
    assert "data" in flat, spec

    def it():
        rng = np.random.default_rng(19)
        w_true = np.random.default_rng(7).normal(size=(hidden, hidden)).astype(np.float32) * 0.3
        while True:
            x = rng.normal(size=(16, hidden)).astype(np.float32)
            yield x, np.tanh(x @ w_true)

    gen = it()
    losses = [float(jax.device_get(engine.train_batch(gen))) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.9, losses
