"""First-class tensor parallelism (SURVEY §2.3: the reference delegated TP to Megatron's
external mpu; here Megatron-style layouts are built in).

Covers both TP flavors on the 8-device virtual CPU platform:
- GSPMD: GPT2Model.param_shardings over a data×model mesh through the full engine —
  losses must match the model=1 run bit-for-bit-ish (same math, different partitioning).
- Manual (shard_map): GPT2Pipe(tp=2) on a pipe×data×model 3D mesh — the Megatron
  psum forward with rank-grouped qkv shards must match the dense model's loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, qkv_tp_permutation
from deepspeed_tpu.models.gpt2_pipe import GPT2Pipe
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

CFG = dict(vocab_size=96, n_positions=32, n_embd=32, n_layer=4, n_head=4,
           compute_dtype=jnp.float32)


def _data(batch=8, seq=16, vocab=96, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
    labels = np.roll(toks, -1, 1)
    return toks, labels


def _run_engine(mesh, param_shardings, steps=3):
    model = GPT2Model(GPT2Config(**CFG))
    params = model.init(jax.random.PRNGKey(7))
    engine = DeepSpeedEngine(
        model=model, model_parameters=params, mesh=mesh, param_shardings=param_shardings,
        config_params={"train_batch_size": 8, "steps_per_print": 100,
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                       "zero_optimization": {"stage": 2}})
    toks, labels = _data()
    losses = []
    for _ in range(steps):
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


@pytest.mark.slow  # two engine builds (~23s); TP parity also pinned by the 3D test
def test_gspmd_tp_matches_replicated(eight_devices):
    base = _run_engine(build_mesh(data=8, model=1, pipe=1), None)

    mesh = build_mesh(data=4, model=2, pipe=1)
    model = GPT2Model(GPT2Config(**CFG))
    tp = _run_engine(mesh, model.param_shardings(mesh))

    assert tp == pytest.approx(base, rel=2e-5, abs=2e-5), f"base={base} tp={tp}"


def test_gspmd_tp_weights_actually_sharded(eight_devices):
    mesh = build_mesh(data=4, model=2, pipe=1)
    model = GPT2Model(GPT2Config(**CFG))
    params = model.init(jax.random.PRNGKey(0))
    sh = model.param_shardings(mesh)
    placed = jax.device_put(params, sh)
    w = placed["blocks"][0]["attn"]["c_attn_w"]
    # column-parallel: each model rank holds half the output columns
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    assert shard_shapes == {(32, 3 * 32 // 2)}, shard_shapes


def test_qkv_tp_permutation_is_rank_grouped_qkv():
    H, tp = 8, 2
    perm = qkv_tp_permutation(H, tp)
    assert sorted(perm.tolist()) == list(range(3 * H))
    # rank 0's contiguous shard = [q_0, k_0, v_0]
    r0 = perm[:3 * H // tp]
    np.testing.assert_array_equal(r0[:4], np.arange(0, 4))          # q first half
    np.testing.assert_array_equal(r0[4:8], np.arange(H, H + 4))     # k first half
    np.testing.assert_array_equal(r0[8:12], np.arange(2 * H, 2 * H + 4))  # v first half


def test_pipe_3d_tp_loss_matches_dense(eight_devices):
    """pipe=2 × data=2 × model=2: the full 3D path vs the plain dense model."""
    mesh = build_mesh(pipe=2, data=2, model=2)
    cfg = GPT2Config(**CFG)
    dense = GPT2Model(cfg)
    dense_params = dense.init(jax.random.PRNGKey(3))

    pipe = GPT2Pipe(cfg, num_stages=2, tp=2)
    pipe_params = pipe.from_dense(jax.tree_util.tree_map(lambda x: x, dense_params))
    shardings = pipe.param_shardings(mesh, pipe_params)
    pipe_params = jax.device_put(pipe_params, shardings)

    M = 2
    toks, labels = _data(batch=2 * M * 2, seq=16)
    toks_mb = jnp.asarray(toks).reshape(M, 4, 16)
    labels_mb = jnp.asarray(labels).reshape(M, 4, 16)

    got = float(jax.jit(lambda p, t, l: pipe.loss(p, t, l, mesh=mesh))(
        pipe_params, toks_mb, labels_mb))

    want = float(np.mean([float(dense.apply(dense_params, np.asarray(toks_mb[m]),
                                            np.asarray(labels_mb[m]))) for m in range(M)]))
    assert got == pytest.approx(want, rel=2e-5, abs=2e-5), f"pipe3d={got} dense={want}"


def test_pipe_3d_weights_sharded_over_pipe_and_model(eight_devices):
    mesh = build_mesh(pipe=2, data=2, model=2)
    cfg = GPT2Config(**CFG)
    pipe = GPT2Pipe(cfg, num_stages=2, tp=2)
    params = pipe.init(jax.random.PRNGKey(0))
    placed = jax.device_put(params, pipe.param_shardings(mesh, params))
    w = placed["stages"]["attn"]["c_attn_w"]          # [S, L/S, H, 3H]
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    assert shard_shapes == {(1, 2, 32, 3 * 32 // 2)}, shard_shapes
