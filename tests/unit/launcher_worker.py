"""Training script spawned by the real multi-process launcher test.

Joins the jax.distributed world from the DS_* env that ``launcher/launch.py``
exports (or runs single-process when none is set), trains SimpleModel for a few
steps on deterministic data, and has process 0 write the loss trajectory to
``--out``. The parent test asserts loss parity between a 2-process world and a
single-process run over the same 2-device mesh (reference test strategy:
tests/unit/common.py:14-100 forks real ranks on one host).
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
sys.path.insert(0, _REPO)
sys.path.insert(0, _HERE)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # before any backend/distributed init

import numpy as np  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--local_rank", type=int, default=0)
    parser.add_argument("--out", type=str, required=True)
    parser.add_argument("--steps", type=int, default=3)
    args = parser.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.runtime import dist as ds_dist

    ds_dist.init_distributed()  # no-op single-process; joins the world under the launcher

    from simple_model import SimpleModel, random_dataset, simple_config

    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=simple_config(batch=8))
    data = random_dataset(8 * args.steps, 16, seed=42)
    losses = []
    for i in range(args.steps):
        xs = np.stack([data[i * 8 + j][0] for j in range(8)])
        ys = np.stack([data[i * 8 + j][1] for j in range(8)])
        loss = engine(xs, ys)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))

    if jax.process_index() == 0:
        with open(args.out, "w") as f:
            json.dump({"losses": losses,
                       "world": jax.process_count(),
                       "devices": jax.device_count()}, f)


if __name__ == "__main__":
    main()
