"""Training script spawned by the real multi-process launcher test.

Joins the jax.distributed world from the DS_* env that ``launcher/launch.py``
exports (or runs single-process when none is set), trains SimpleModel for a few
steps on deterministic data, and has process 0 write the loss trajectory to
``--out``. The parent test asserts loss parity between a 2-process world and a
single-process run over the same 2-device mesh (reference test strategy:
tests/unit/common.py:14-100 forks real ranks on one host).
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
sys.path.insert(0, _REPO)
sys.path.insert(0, _HERE)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # before any backend/distributed init

import numpy as np  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--local_rank", type=int, default=0)
    parser.add_argument("--out", type=str, required=True)
    parser.add_argument("--steps", type=int, default=3)
    parser.add_argument("--offload", action="store_true",
                        help="ZeRO-2 + cpu_offload: each process steps and "
                             "checkpoints only its own host-tier regions")
    parser.add_argument("--ckpt_dir", type=str, default=None)
    args = parser.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.runtime import dist as ds_dist

    ds_dist.init_distributed()  # no-op single-process; joins the world under the launcher

    from simple_model import SimpleModel, random_dataset, simple_config

    hidden = 64 if args.offload else 16  # 64 -> leaves big enough for real ZeRO regions
    model = SimpleModel(hidden_dim=hidden)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=8)
    if args.offload:
        cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    data = random_dataset(8 * args.steps, hidden, seed=42)
    losses = []
    for i in range(args.steps):
        xs = np.stack([data[i * 8 + j][0] for j in range(8)])
        ys = np.stack([data[i * 8 + j][1] for j in range(8)])
        loss = engine(xs, ys)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))

    result = {"losses": losses, "world": jax.process_count(),
              "devices": jax.device_count()}
    if args.ckpt_dir:
        # every process writes its offload regions; process 0 writes the rest
        engine.save_checkpoint(args.ckpt_dir, tag="t0")
        if args.offload:
            result["local_numel"] = int(engine._offload.numel)
            result["n_regions"] = sum(len(r) for r in engine._offload._leaf_regions)
            # round-trip into a FRESH engine in this same world: the loader reads
            # every process's region files and scatters back only local regions
            params2 = model.init(jax.random.PRNGKey(0))
            engine2, _, _, _ = deepspeed_tpu.initialize(
                model=model, model_parameters=params2, config_params=cfg)
            engine2.load_checkpoint(args.ckpt_dir)
            np.testing.assert_allclose(engine2._offload.fp32, engine._offload.fp32,
                                       rtol=1e-6)
            np.testing.assert_allclose(engine2._offload.exp_avg,
                                       engine._offload.exp_avg, rtol=1e-6)
            result["roundtrip_ok"] = True
    if jax.process_index() == 0:
        with open(args.out, "w") as f:
            json.dump(result, f)


if __name__ == "__main__":
    main()
