"""Training script spawned by the real multi-process launcher test.

Joins the jax.distributed world from the DS_* env that ``launcher/launch.py``
exports (or runs single-process when none is set), trains SimpleModel for a few
steps on deterministic data, and has process 0 write the loss trajectory to
``--out``. The parent test asserts loss parity between a 2-process world and a
single-process run over the same 2-device mesh (reference test strategy:
tests/unit/common.py:14-100 forks real ranks on one host).
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
if __name__ == "__main__":
    # spawned-worker bootstrap ONLY: an importing host (pytest, the dry run)
    # already has its platform pinned and must not get tests/unit at
    # sys.path[0], where generically named modules (simple_model) would shadow
    sys.path.insert(0, _REPO)
    sys.path.insert(0, _HERE)

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")  # before any backend/distributed init

import numpy as np  # noqa: E402


def clean_spawn_env(**extra):
    """Environment for spawned multi-process workers with every distributed-
    identity / platform-pinning variable scrubbed (a stale RANK/TPU_* var from
    the host process would corrupt the spawned world). Single source of truth —
    test_launcher.py and __graft_entry__'s rehearsal both use it."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("DS_", "TPU_", "CLOUD_TPU"))
           and k not in ("XLA_FLAGS", "MASTER_ADDR", "MASTER_PORT", "RANK",
                         "WORLD_SIZE", "LOCAL_RANK", "JAX_PLATFORMS")}
    env.update(extra)
    return env


def free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_elastic_rehearsal(tmp, repo_root, timeout=420):
    """Three-phase sharded-state lifecycle rehearsal, shared by
    tests/unit/test_launcher.py and __graft_entry__'s multichip dry run:
    (A) 2 launcher-spawned jax.distributed processes train ZeRO-2+offload and
    save per-process region files; (B) a fresh 1-process engine (2 virtual
    devices — same global math) ELASTICALLY reloads the 2-process checkpoint
    and continues; (C) an uninterrupted single-process oracle. Returns the
    three result dicts after asserting B continues C step-for-step."""
    import base64
    import subprocess

    import numpy as np

    def clean_env(**extra):
        return clean_spawn_env(PYTHONPATH=repo_root, **extra)

    worker = os.path.abspath(__file__)
    ckpt = os.path.join(tmp, "ckpt")
    port = free_port()
    world_info = base64.urlsafe_b64encode(
        json.dumps({"localhost": [0, 1]}).encode()).decode()
    out_a, out_b, out_c = (os.path.join(tmp, f"{x}.json") for x in "abc")

    r = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.launch", "--node_rank=0",
         "--master_addr=127.0.0.1", f"--master_port={port}",
         f"--world_info={world_info}", worker,
         f"--out={out_a}", "--steps=3", "--offload", f"--ckpt_dir={ckpt}"],
        env=clean_env(), capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, f"phase A failed:\n{r.stdout[-800:]}\n{r.stderr[-1500:]}"
    env1 = clean_env(XLA_FLAGS="--xla_force_host_platform_device_count=2")
    r = subprocess.run(
        [sys.executable, worker, f"--out={out_b}", "--steps=2", "--offload",
         f"--ckpt_dir={ckpt}", "--load", "--data_offset=3"],
        env=env1, capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, f"phase B failed:\n{r.stdout[-800:]}\n{r.stderr[-1500:]}"
    r = subprocess.run(
        [sys.executable, worker, f"--out={out_c}", "--steps=5", "--offload"],
        env=env1, capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, f"phase C failed:\n{r.stderr[-1500:]}"

    a, b, c = (json.load(open(p)) for p in (out_a, out_b, out_c))
    assert a["world"] == 2 and a["roundtrip_ok"], a
    assert b["world"] == 1 and b["devices"] == 2, b
    np.testing.assert_allclose(a["losses"], c["losses"][:3], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(b["losses"], c["losses"][3:], rtol=1e-5, atol=1e-6)
    return a, b, c


def run_hierarchical_rehearsal(tmp, repo_root, timeout=420):
    """Two-level-comm multi-process rehearsal, shared by test_launcher.py and
    __graft_entry__'s multichip dry run. Two launcher-spawned jax.distributed
    processes x 2 virtual devices each = dp 4, auto-factorized into 2 slices of
    2 (the DCN boundary IS the process boundary):

    (A) ZeRO-2 + Adam with ``comm.mode=hierarchical`` vs (C) a single-process
        flat engine over the same 4-device global math — loss parity within the
        two-level reassociation tolerance;
    (B) stage-0 OneBitAdam(freeze_step=2) with ``hierarchical_compressed`` vs
        (D) the same optimizer flat — warmup steps are the identical
        uncompressed mean (tight), compressed steps stay within the documented
        1-bit tolerance and keep training.
    Returns the four result dicts."""
    import base64
    import subprocess

    import numpy as np

    def clean_env(**extra):
        return clean_spawn_env(PYTHONPATH=repo_root, **extra)

    worker = os.path.abspath(__file__)
    world_info = base64.urlsafe_b64encode(
        json.dumps({"localhost": [0, 1]}).encode()).decode()
    outs = {x: os.path.join(tmp, f"hier_{x}.json") for x in "abcd"}
    two_dev = "--xla_force_host_platform_device_count=2"
    four_dev = "--xla_force_host_platform_device_count=4"

    def launch_two(out, *extra):
        port = free_port()
        return subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
             "--node_rank=0", "--master_addr=127.0.0.1",
             f"--master_port={port}", f"--world_info={world_info}", worker,
             f"--out={out}", "--steps=4", *extra],
            env=clean_env(XLA_FLAGS=two_dev), capture_output=True, text=True,
            timeout=timeout)

    def solo(out, *extra):
        return subprocess.run(
            [sys.executable, worker, f"--out={out}", "--steps=4", *extra],
            env=clean_env(XLA_FLAGS=four_dev), capture_output=True, text=True,
            timeout=timeout)

    r = launch_two(outs["a"], "--zero_stage=2", "--comm_mode=hierarchical")
    assert r.returncode == 0, f"phase A failed:\n{r.stdout[-800:]}\n{r.stderr[-1500:]}"
    r = solo(outs["c"], "--zero_stage=2")
    assert r.returncode == 0, f"phase C failed:\n{r.stderr[-1500:]}"
    r = launch_two(outs["b"], "--optimizer=onebit",
                   "--comm_mode=hierarchical_compressed")
    assert r.returncode == 0, f"phase B failed:\n{r.stdout[-800:]}\n{r.stderr[-1500:]}"
    r = solo(outs["d"], "--optimizer=onebit")
    assert r.returncode == 0, f"phase D failed:\n{r.stderr[-1500:]}"

    a, b, c, d = (json.load(open(outs[x])) for x in "abcd")
    assert a["world"] == 2 and a["devices"] == 4, a
    assert (a["num_slices"], a["slice_size"]) == (2, 2), a
    assert b["world"] == 2 and (b["num_slices"], b["slice_size"]) == (2, 2), b
    assert c["num_slices"] == 1 and d["num_slices"] == 1, (c, d)
    # hierarchical vs flat: same mean, reassociated — tolerance, not bits
    np.testing.assert_allclose(a["losses"], c["losses"], rtol=2e-3, atol=2e-4)
    # 1-bit warmup (steps 1-2) is the identical uncompressed mean
    np.testing.assert_allclose(b["losses"][:2], d["losses"][:2],
                               rtol=1e-4, atol=1e-5)
    # compressed steps: documented 1-bit tolerance, and still training
    assert max(abs(x - y) for x, y in zip(b["losses"][2:], d["losses"][2:])) < 0.1, \
        (b["losses"], d["losses"])
    assert b["losses"][-1] < b["losses"][0], b["losses"]
    return a, b, c, d


def run_cluster_observatory_rehearsal(tmp, repo_root, timeout=420):
    """Cluster-observatory multi-process rehearsal, shared by
    test_launcher.py and __graft_entry__'s multichip dry run. Two
    launcher-spawned jax.distributed processes with ``telemetry.cluster``
    enabled (docs/cluster.md):

    (A) straggler phase — rank 1 sleeps 150 ms inside every step's dispatch
        window; rank 0's heartbeat aggregation must NAME host 1 as the
        straggler (the end-to-end wall is collective-equalised, so this
        exercises the host-local dispatch column end to end);
    (B) stall phase — rank 1 sleeps 2 s inside one armed step against a
        0.5 s hang deadline; BOTH hosts must write flight-recorder dumps
        (rank 1 by deadline expiry, rank 0 either by its own expiry while
        blocked in the stalled collective or by the peer marker), and
        ``ds-tpu cluster-dump`` must assemble them into one report naming a
        stalled host and the scope it died in.
    Returns the two result dicts (rank 0's, per phase)."""
    import base64
    import subprocess

    def clean_env(**extra):
        return clean_spawn_env(PYTHONPATH=repo_root, **extra)

    worker = os.path.abspath(__file__)
    world_info = base64.urlsafe_b64encode(
        json.dumps({"localhost": [0, 1]}).encode()).decode()
    two_dev = "--xla_force_host_platform_device_count=2"

    def launch_two(out, *extra):
        port = free_port()
        return subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
             "--node_rank=0", "--master_addr=127.0.0.1",
             f"--master_port={port}", f"--world_info={world_info}", worker,
             f"--out={out}", "--cluster", *extra],
            env=clean_env(XLA_FLAGS=two_dev), capture_output=True, text=True,
            timeout=timeout)

    # (A) straggler: per-step sleep on rank 1, generous 5-step window
    out_a = os.path.join(tmp, "cluster_a.json")
    r = launch_two(out_a, "--steps=5", "--straggle_ms=150")
    assert r.returncode == 0, \
        f"straggler phase failed:\n{r.stdout[-800:]}\n{r.stderr[-1500:]}"
    a = json.load(open(out_a))
    assert a["world"] == 2, a
    ca = a["cluster"]
    assert ca["hosts"] == 2 and ca["heartbeats"] >= 5, ca
    assert ca["straggler_host"] == 1, \
        f"rank 1 slept 150ms/step but straggler naming said {ca!r}"
    assert ca["straggler_events"] >= 1 and ca["watchdog_fired"] == 0, ca

    # (B) stall: one 2s sleep inside an armed step vs a 0.5s deadline
    out_b = os.path.join(tmp, "cluster_b.json")
    dumps = os.path.join(tmp, "cluster_dumps")
    r = launch_two(out_b, "--steps=4", "--hang_deadline_s=0.5",
                   "--stall_step=2", "--stall_ms=2000",
                   f"--cluster_dump_dir={dumps}")
    assert r.returncode == 0, \
        f"stall phase failed:\n{r.stdout[-800:]}\n{r.stderr[-1500:]}"
    b = json.load(open(out_b))
    assert b["cluster"]["watchdog_fired"] >= 1, b["cluster"]

    from deepspeed_tpu.utils.cluster import assemble_cluster_report
    from deepspeed_tpu.utils.numerics import load_run_bundles
    run_key, by_host = load_run_bundles(dumps)
    assert sorted(by_host) == [0, 1], \
        f"expected dumps from both hosts in {dumps}, got {sorted(by_host)}"
    report = assemble_cluster_report(by_host, run_key)
    stall = report["first_stall"]
    assert stall is not None and stall["host"] in (0, 1), report
    assert stall["step"] == 2 and stall["scope"], report
    return a, b


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--local_rank", type=int, default=0)
    parser.add_argument("--out", type=str, required=True)
    parser.add_argument("--steps", type=int, default=3)
    parser.add_argument("--offload", action="store_true",
                        help="ZeRO-2 + cpu_offload: each process steps and "
                             "checkpoints only its own host-tier regions")
    parser.add_argument("--ckpt_dir", type=str, default=None)
    parser.add_argument("--load", action="store_true",
                        help="load --ckpt_dir BEFORE training (elastic: the saved "
                             "world size may differ from this run's)")
    parser.add_argument("--data_offset", type=int, default=0,
                        help="skip this many steps of the deterministic stream "
                             "(resume continuity)")
    parser.add_argument("--zero_stage", type=int, default=0,
                        help="plain ZeRO stage (no offload) for the comm runs")
    parser.add_argument("--comm_mode", type=str, default="",
                        help="comm.mode config ('' = flat default); dcn_slices "
                             "auto-derives from the jax.distributed world")
    parser.add_argument("--optimizer", type=str, default="adam",
                        choices=["adam", "onebit"],
                        help="onebit = OneBitAdam(freeze_step=2): warmup is the "
                             "uncompressed mean, later steps 1-bit compressed")
    parser.add_argument("--cluster", action="store_true",
                        help="enable telemetry + telemetry.cluster (heartbeat "
                             "aggregation, straggler naming, hang watchdog)")
    parser.add_argument("--cluster_dump_dir", type=str, default="",
                        help="shared hang-dump dir (also carries the peer "
                             "hang markers)")
    parser.add_argument("--hang_deadline_s", type=float, default=0.0,
                        help="per-step watchdog deadline; 0 = watchdog off")
    parser.add_argument("--straggle_ms", type=float, default=0.0,
                        help="rank 1 sleeps this long inside every step's "
                             "dispatch window (straggler injection)")
    parser.add_argument("--stall_step", type=int, default=-1,
                        help="rank 1 sleeps --stall_ms once at this step, "
                             "while the watchdog is armed (hang injection)")
    parser.add_argument("--stall_ms", type=float, default=0.0)
    args = parser.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.runtime import dist as ds_dist

    ds_dist.init_distributed()  # no-op single-process; joins the world under the launcher

    from simple_model import SimpleModel, random_dataset, simple_config

    hidden = 64 if args.offload else 16  # 64 -> leaves big enough for real ZeRO regions
    model = SimpleModel(hidden_dim=hidden)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=8)
    if args.offload:
        cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    if args.zero_stage:
        cfg["zero_optimization"] = {"stage": args.zero_stage}
    if args.optimizer == "onebit":
        cfg["optimizer"] = {"type": "OneBitAdam",
                            "params": {"lr": 1e-2, "freeze_step": 2}}
    if args.comm_mode:
        cfg["comm"] = {"mode": args.comm_mode}
    if args.cluster:
        cfg["telemetry"] = {
            "enabled": True,
            "cluster": {"enabled": True, "heartbeat_interval": 1,
                        "hang_deadline_s": args.hang_deadline_s,
                        "dump_dir": args.cluster_dump_dir,
                        "straggler_threshold": 3.0,
                        # steps 0-1 compile loss_and_grad + apply_update;
                        # their walls are compile jitter, not signal
                        "warmup_steps": 2}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    if args.load:
        # elastic path: region files on disk may come from a DIFFERENT world size
        # (the loader merges every saved process's regions and re-scatters locals)
        engine.load_checkpoint(args.ckpt_dir)
    data = random_dataset(8 * (args.data_offset + args.steps), hidden, seed=42)
    losses = []
    import time as _time
    for i in range(args.data_offset, args.data_offset + args.steps):
        xs = np.stack([data[i * 8 + j][0] for j in range(8)])
        ys = np.stack([data[i * 8 + j][1] for j in range(8)])
        loss = engine(xs, ys)
        engine.backward(loss)
        # cluster-observatory fault injection: sleeps land between backward
        # and step, i.e. inside this host's dispatch window while the hang
        # watchdog is armed — exactly where a slow input pipeline or a wedged
        # host-side stage would stall a real run
        if jax.process_index() == 1:
            if args.straggle_ms > 0:
                _time.sleep(args.straggle_ms / 1000.0)
            if args.stall_ms > 0 and (i - args.data_offset) == args.stall_step:
                _time.sleep(args.stall_ms / 1000.0)
        engine.step()
        losses.append(float(jax.device_get(loss)))

    result = {"losses": losses, "world": jax.process_count(),
              "devices": jax.device_count(),
              "num_slices": engine._comm_topo.num_slices,
              "slice_size": engine._comm_topo.slice_size}
    if args.cluster and engine._cluster is not None:
        # give a stalled peer's watchdog time to finish its dump before this
        # process exits (the launcher reaps children on first exit)
        if args.stall_ms > 0:
            _time.sleep(0.5)
        result["cluster"] = engine._cluster.summary()
        engine._cluster.stop()
    if args.ckpt_dir and not args.load:
        # every process writes its offload regions; process 0 writes the rest
        engine.save_checkpoint(args.ckpt_dir, tag="t0")
        if args.offload:
            result["local_numel"] = int(engine._offload.numel)
            result["n_regions"] = sum(len(r) for r in engine._offload._leaf_regions)
            # round-trip into a FRESH engine in this same world: the loader reads
            # every process's region files and scatters back only local regions
            params2 = model.init(jax.random.PRNGKey(0))
            engine2, _, _, _ = deepspeed_tpu.initialize(
                model=model, model_parameters=params2, config_params=cfg)
            engine2.load_checkpoint(args.ckpt_dir)
            np.testing.assert_allclose(engine2._offload.fp32, engine._offload.fp32,
                                       rtol=1e-6)
            np.testing.assert_allclose(engine2._offload.exp_avg,
                                       engine._offload.exp_avg, rtol=1e-6)
            result["roundtrip_ok"] = True
    if jax.process_index() == 0:
        with open(args.out, "w") as f:
            json.dump(result, f)


if __name__ == "__main__":
    main()
