"""ZeRO Stage 3 — full parameter sharding (beyond the v0.3.0 reference).

The reference stops at stage 2 (runtime/zero/constants.py MAX_STAGE = gradients);
stage 3 (the later ZeRO-3 / FSDP) shards the compute parameters themselves over the
data axis. On TPU that is a GSPMD layout: ``zero_spec`` annotates the bf16 params,
XLA all-gathers each leaf at its use point in forward/backward, grads live
reduce-scattered (stage-2 layout), and the updated fp32 master casts back into the
sharded param layout — per-device parameter HBM scales as 1/dp with no hand-rolled
gather/partition machinery (the reference's stage2.py flatten/partition analog).
"""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from oldjax import grad_through_shard_map_xfail
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import DATA_AXIS, build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.hlo import collective_counts, optimized_hlo

from simple_model import SimpleModel, random_dataset, simple_config


H = 64  # dp=8-divisible so every weight matrix shards


def _engine(stage, hidden=H, batch=8, cpu_offload=False, **cfg):
    model = SimpleModel(hidden)
    params = model.init(jax.random.PRNGKey(0))
    zero = {"stage": stage, "cpu_offload": cpu_offload}
    return DeepSpeedEngine(
        model=model, model_parameters=params,
        config_params=simple_config(batch=batch, zero_optimization=zero,
                                    bf16={"enabled": True}, **cfg))


def _run_steps(eng, n=5, hidden=H, batch=8):
    data = random_dataset(batch * n, hidden)
    losses = []
    for i in range(n):
        xs = np.stack([data[i * batch + j][0] for j in range(batch)])
        ys = np.stack([data[i * batch + j][1] for j in range(batch)])
        loss = eng.forward(xs, ys)
        eng.backward(loss)
        eng.step()
        losses.append(float(loss))
    return losses


def test_zero3_shards_compute_params():
    eng = _engine(3)
    mats = [(k, v) for k, v in eng.params.items() if v.ndim == 2]
    assert mats
    for name, leaf in mats:
        assert not leaf.sharding.is_fully_replicated, f"{name} not sharded under stage 3"
        # per-device shard holds 1/dp of the leaf
        local = leaf.addressable_shards[0].data.size
        assert local * 8 == leaf.size, (name, local, leaf.size)
    # stage 2 leaves compute params replicated — the stage-3 delta is exactly the params
    eng2 = _engine(2)
    for _, leaf in [(k, v) for k, v in eng2.params.items() if v.ndim == 2]:
        assert leaf.sharding.is_fully_replicated


def test_zero3_trains_and_matches_stage0():
    """Same init + data: stage 3 is a layout, not an algorithm — losses must track
    the replicated stage-0 run to float tolerance."""
    l3 = _run_steps(_engine(3))
    l0 = _run_steps(_engine(0))
    assert l3[-1] < l3[0], l3
    np.testing.assert_allclose(l3, l0, rtol=2e-2, atol=2e-3)


def test_zero3_forward_all_gathers_params():
    """The compiled train step must materialize sharded params via all-gather at use
    (ZeRO-3's gather-on-use, emitted by the partitioner instead of hand-rolled)."""
    eng = _engine(3)
    x = jnp.ones((8, H))
    txt = optimized_hlo(eng._jit_loss_and_grad, eng.params,
                        eng.scaler_state.cur_scale, x, x)
    counts = collective_counts(txt)
    assert counts.get("all-gather", 0) >= 1, \
        f"stage-3 forward/backward has no param all-gather: {counts}"


def test_zero3_checkpoint_roundtrip(tmp_path):
    eng = _engine(3)
    _run_steps(eng, n=3)
    eng.save_checkpoint(str(tmp_path), tag="z3")
    ref = jax.tree_util.tree_map(np.asarray, eng.params)

    eng2 = _engine(3)
    eng2.load_checkpoint(str(tmp_path), tag="z3")
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(eng2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored params keep the stage-3 sharded layout
    for k, v in eng2.params.items():
        if v.ndim == 2:
            assert not v.sharding.is_fully_replicated


def test_zero3_composes_with_offload():
    """Stage 3 + cpu_offload: compute params sharded over data AND master/moments
    in the host tier (beyond-reference composition — the offload regions are
    partitioned by the same master layout stage 3 gives the params). Trajectory
    must match stage 2 + offload exactly (layouts don't change the math)."""
    l3 = _run_steps(_engine(3, cpu_offload=True), n=6)
    l2 = _run_steps(_engine(2, cpu_offload=True), n=6)
    assert l3[-1] < l3[0], l3
    np.testing.assert_allclose(l3, l2, rtol=1e-6, atol=1e-6)
    eng = _engine(3, cpu_offload=True)
    assert eng._offload is not None
    for name, leaf in eng.params.items():
        if leaf.ndim == 2:
            assert not leaf.sharding.is_fully_replicated, name


@grad_through_shard_map_xfail
def test_zero3_composes_with_spmd_pipeline():
    """Public-API PipelineModule + stage 3: ZeRO claims a free data-divisible axis
    ON TOP of the pipe-stacked stage layout for the compute params too (true
    param sharding under 2D pipe x data), and the engine still trains."""
    import deepspeed_tpu
    from deepspeed_tpu.parallel.pipe import LayerSpec, PipelineModule

    class Linear:
        def __init__(self, dim):
            self.dim = dim

        def init(self, rng, x):
            return {"w": jax.random.normal(rng, (x.shape[-1], self.dim),
                                           jnp.float32) * 0.3}

        def apply(self, p, x):
            return jnp.tanh(x @ p["w"].astype(x.dtype))

    module = PipelineModule(layers=[LayerSpec(Linear, 64) for _ in range(4)],
                            num_stages=2,
                            loss_fn=lambda out, tgt: jnp.mean((out - tgt) ** 2))
    params = module.init_params(jax.random.PRNGKey(0), jnp.zeros((4, 64)))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config_params={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                       "bf16": {"enabled": True},
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                       "zero_optimization": {"stage": 3}})
    assert engine._spmd
    # stage-3 delta vs stage 2: COMPUTE params carry the merged (pipe+data) layout
    sharded = [l for l in jax.tree_util.tree_leaves(engine.params)
               if sum(ax is not None for ax in l.sharding.spec) >= 2]
    assert sharded, "no compute param is sharded over both pipe and data axes"

    rng = np.random.default_rng(0)
    losses = []
    for _ in range(6):
        x = rng.normal(size=(8, 64)).astype(np.float32)
        losses.append(float(engine.train_batch(iter([(x, np.tanh(x))] * 2))))
    assert losses[-1] < losses[0], losses


def test_zero3_config_validation():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    cfg = DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True},
                           "zero_optimization": {"stage": 3}}, world_size=8)
    assert cfg.zero_optimization_stage == 3
    with pytest.raises(AssertionError):
        DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True},
                         "zero_optimization": {"stage": 4}}, world_size=8)
    # cpu_offload composes with stage 3 (host master + sharded compute params);
    # stage 1 still rejects it
    cfg3 = DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True},
                            "zero_optimization": {"stage": 3, "cpu_offload": True}},
                           world_size=8)
    assert cfg3.zero_config.cpu_offload
    with pytest.raises(AssertionError):
        DeepSpeedConfig({"train_batch_size": 8, "bf16": {"enabled": True},
                         "zero_optimization": {"stage": 1, "cpu_offload": True}},
                        world_size=8)
