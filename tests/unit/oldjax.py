"""Version gates for tests that exercise jax APIs fixed after 0.4.x.

``grad_through_shard_map_xfail`` marks tests that differentiate THROUGH a
shard_map'd pipeline/train step: ``jax.experimental.shard_map``'s transpose
rule materializes symbolic-zero cotangents as scalars and then fails its own
``_check_names`` against the dim-named in_specs (``_SpecError``). The
top-level ``jax.shard_map`` (jax >= 0.5) transposes these correctly, so the
gate is conditional on its presence — on a current jax these tests must pass.
"""

import jax
import pytest

grad_through_shard_map_xfail = pytest.mark.xfail(
    not hasattr(jax, "shard_map"),
    reason="jax.experimental.shard_map transpose _SpecError under grad through "
           "the shard_map'd step (fixed by the top-level jax.shard_map in "
           "jax >= 0.5)",
    strict=False)
