"""Paged KV-cache numerics.

The load-bearing bitwise pair is paged <-> serve/oracle.py: the oracle's dense
cached programs are written with the same op structure (same einsum shapes,
same mask widths, same write-then-read order) so XLA compiles the same
arithmetic and the logits match BIT FOR BIT — that is the invariant the
engine's mirror mode and ds-tpu serve-sim replay at scale.

Against the model's own ``_build_cached_forward`` the guarantee is weaker:
same math, but a DIFFERENT jit program (contiguous cache, no page gather), so
XLA may fuse differently and individual logits can land 1 ulp apart for some
inputs (observed: 3e-08 on one of four random prompts on CPU). We pin that
comparison to float tolerance + argmax-token agreement, not bits.

The Pallas decode kernel reduces page-by-page (online softmax) and is pinned
to float tolerance against the flat-softmax gather reference."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.block_allocator import BlockAllocator
from deepspeed_tpu.serve.oracle import build_oracle_programs
from deepspeed_tpu.serve.paged import build_paged_programs

S, BS, MB, C = 4, 4, 8, 8          # slots, block size, table width, chunk
ML = MB * BS                       # 32
NB = 33                            # pool pages (1 null + 32)


@pytest.fixture(scope="module")
def setup():
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    progs = build_paged_programs(model, num_slots=S, block_size=BS,
                                 max_blocks=MB, prefill_chunk=C)
    oracle = build_oracle_programs(model, num_slots=S, max_len=ML,
                                   prefill_chunk=C)
    return model, params, progs, oracle


def _paged_state(model):
    c = model.config
    shape = (c.n_layer, NB, BS, c.n_head, c.head_dim)
    return jnp.zeros(shape, c.compute_dtype), jnp.zeros(shape, c.compute_dtype)


def test_paged_decode_bitwise_matches_dense_oracle(setup):
    """Prefill S sequences through the paged path AND the dense-cache oracle,
    then decode 6 greedy steps at [S, 1] in lockstep — every logit row must be
    bit-identical at every step. The model's own ``_build_cached_forward`` is
    held to tolerance + identical argmax tokens (different jit program ->
    fusion may round 1 ulp apart; see module docstring)."""
    model, params, progs, oracle = setup
    T0, steps = C, 6                            # one full chunk per prompt
    rng = np.random.RandomState(0)
    prompts = rng.randint(0, 64, size=(S, T0)).astype(np.int32)

    # paged prefill: one chunk per sequence through its block table (pages
    # for the whole prompt + decode horizon up front — the engine's scheduler
    # grows tables one page per step instead)
    alloc = BlockAllocator(NB, BS)
    kp, vp = _paged_state(model)
    okcs, ovcs = oracle["fresh_caches"]()
    tbl = np.zeros((S, MB), np.int32)
    fwd = model._build_cached_forward(ML)
    c = model.config
    kcs = jnp.zeros((c.n_layer, S, c.n_head, ML, c.head_dim), c.compute_dtype)
    vcs = jnp.zeros_like(kcs)
    paged_first = []
    for s in range(S):
        t = alloc.allocate(alloc.blocks_for_tokens(T0 + steps))
        tbl[s, :len(t)] = t
        plg, kp, vp = progs["prefill_chunk"](
            params, jnp.asarray(prompts[s:s + 1]), jnp.int32(0),
            jnp.int32(T0), jnp.asarray(tbl[s]), kp, vp)
        olg, okcs, ovcs = oracle["prefill_chunk"](
            params, jnp.asarray(prompts[s:s + 1]), jnp.int32(0),
            jnp.int32(T0), jnp.int32(s), okcs, ovcs)
        np.testing.assert_array_equal(np.asarray(plg[0]), np.asarray(olg[0]))
        paged_first.append(np.asarray(plg[0]))

    # model forward reference: [S, T0] batched prefill, tolerance only
    flg, kcs, vcs = fwd(params, jnp.asarray(prompts), 0, kcs, vcs)
    np.testing.assert_allclose(np.asarray(paged_first), np.asarray(flg),
                               atol=1e-5)

    # greedy decode lockstep at [S, 1] on all three, 6 tokens
    toks = np.argmax(np.asarray(paged_first), axis=1).astype(np.int32)
    pos = np.full(S, T0, np.int32)
    active = np.ones(S, bool)
    for _ in range(steps):
        pl_, kp, vp = progs["decode_step"](
            params, jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(tbl),
            jnp.asarray(active), kp, vp)
        ol_, okcs, ovcs = oracle["decode_step"](
            params, jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(active),
            okcs, ovcs)
        dl, kcs, vcs = fwd(params, jnp.asarray(toks[:, None]),
                           int(pos[0]), kcs, vcs)
        np.testing.assert_array_equal(np.asarray(pl_), np.asarray(ol_))
        np.testing.assert_allclose(np.asarray(pl_), np.asarray(dl), atol=1e-5)
        assert (np.argmax(np.asarray(pl_), axis=1)
                == np.argmax(np.asarray(dl), axis=1)).all()
        toks = np.argmax(np.asarray(pl_), axis=1).astype(np.int32)
        pos += 1


def test_chunked_prefill_bitwise_matches_oracle_chunks(setup):
    """Splitting a prompt across chunks must write the identical cache bytes:
    the paged 2-chunk prefill and the oracle fed the same two [1, C] chunks
    agree bitwise through the decode that follows (chunk boundaries change
    gemm shapes, but each position's row math is independent — pinned here)."""
    model, params, progs, oracle = setup
    rng = np.random.RandomState(1)
    T0 = C + 3                                  # forces a second, padded chunk
    prompt = rng.randint(0, 64, size=T0).astype(np.int32)

    alloc = BlockAllocator(NB, BS)
    kp, vp = _paged_state(model)
    okcs, ovcs = oracle["fresh_caches"]()
    t = alloc.allocate(alloc.blocks_for_tokens(T0 + 1))
    tbl = np.zeros(MB, np.int32)
    tbl[:len(t)] = t
    for start in (0, C):
        n = min(C, T0 - start)
        chunk = np.zeros(C, np.int32)
        chunk[:n] = prompt[start:start + n]
        lg, kp, vp = progs["prefill_chunk"](
            params, jnp.asarray(chunk[None]), jnp.int32(start), jnp.int32(n),
            jnp.asarray(tbl), kp, vp)
        og, okcs, ovcs = oracle["prefill_chunk"](
            params, jnp.asarray(chunk[None]), jnp.int32(start), jnp.int32(n),
            jnp.int32(0), okcs, ovcs)
    np.testing.assert_array_equal(np.asarray(lg[0]), np.asarray(og[0]))
    tok = int(np.argmax(np.asarray(lg[0])))

    # decode comparison at [S, 1] with only slot 0 active — the oracle decode
    # runs all S rows, so keep the padded rows' inputs fixed on both sides
    toks = np.zeros(S, np.int32)
    toks[0] = tok
    pos = np.zeros(S, np.int32)
    pos[0] = T0
    tables = np.zeros((S, MB), np.int32)
    tables[0] = tbl
    active = np.zeros(S, bool)
    active[0] = True
    pl_, kp, vp = progs["decode_step"](
        params, jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(tables),
        jnp.asarray(active), kp, vp)
    ol_, okcs, ovcs = oracle["decode_step"](
        params, jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(active),
        okcs, ovcs)
    np.testing.assert_array_equal(np.asarray(pl_[0]), np.asarray(ol_[0]))

    # the model's full (uncached, unchunked) forward agrees on the next token
    full = model.apply(params, jnp.asarray(prompt[None]))
    assert int(np.argmax(np.asarray(full[0, T0 - 1]))) == tok


def test_copy_blocks_copies_pages_and_null_self_copy_is_noop(setup):
    model, params, progs, oracle = setup
    kp, vp = _paged_state(model)
    rng = np.random.RandomState(2)
    kp = jnp.asarray(rng.randn(*kp.shape), kp.dtype)
    vp = jnp.asarray(rng.randn(*vp.shape), vp.dtype)
    before_k = np.asarray(kp)
    src = np.zeros(S, np.int32)
    dst = np.zeros(S, np.int32)
    src[0], dst[0] = 3, 7                        # one real copy, rest pads
    kp2, vp2 = progs["copy_blocks"](kp, vp, jnp.asarray(src),
                                    jnp.asarray(dst))
    after_k = np.asarray(kp2)
    np.testing.assert_array_equal(after_k[:, 7], before_k[:, 3])
    mask = np.ones(NB, bool)
    mask[7] = False
    np.testing.assert_array_equal(after_k[:, mask], before_k[:, mask])


def test_pallas_paged_decode_matches_dense_gather_reference():
    """The opt-in Pallas kernel (online softmax, page-by-page) matches the
    flat-softmax dense gather to float tolerance across history lengths."""
    from deepspeed_tpu.ops.pallas.paged_attention import paged_decode_attention

    rng = np.random.RandomState(0)
    nl, nh, hd = 2, 2, 8
    kp = jnp.asarray(rng.randn(nl, NB, BS, nh, hd), jnp.float32)
    vp = jnp.asarray(rng.randn(nl, NB, BS, nh, hd), jnp.float32)
    q = jnp.asarray(rng.randn(S, nh, 1, hd), jnp.float32)
    tables = jnp.asarray(rng.randint(1, NB, size=(S, MB)), jnp.int32)
    lengths = jnp.asarray([1, 5, BS * MB, 17], jnp.int32)

    for li in range(nl):
        y = paged_decode_attention(q, kp, vp, li, tables, lengths,
                                   block_size=BS)
        g = kp[li][tables].reshape(S, ML, nh, hd).transpose(0, 2, 1, 3)
        gv = vp[li][tables].reshape(S, ML, nh, hd).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, g,
                       preferred_element_type=jnp.float32) / math.sqrt(hd)
        mask = (jnp.arange(ML)[None, :] < lengths[:, None])[:, None, None, :]
        s = jnp.where(mask, s, jnp.float32(-1e9))
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("bhqk,bhkd->bhqd", p, gv,
                         preferred_element_type=jnp.float32)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)
