"""Every accepted config key acts, warns, or errors — never a silent no-op.

Sweeps the TOP_LEVEL_CONFIG_KEYS registry (runtime/constants.py): for each key,
setting a non-default value must either change engine-visible DeepSpeedConfig
state, emit a diagnostic through the package logger, or raise. Mirrors the
reference's error/warning discipline (deepspeed/runtime/config.py:633-670) and
extends it with the TPU-migration diagnostics for keys whose CUDA mechanism
(apex amp, hand-written bucketed collectives, fused-kernel variants) has no
GSPMD analog.
"""

import logging

import numpy as np
import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.constants import TOP_LEVEL_CONFIG_KEYS
from deepspeed_tpu.utils import logger


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.records = []

    def emit(self, record):
        self.records.append(record)

    @property
    def text(self):
        return "\n".join(r.getMessage() for r in self.records)


@pytest.fixture
def capture():
    h = _Capture()
    logger.addHandler(h)
    try:
        yield h
    finally:
        logger.removeHandler(h)


BASE = {"train_batch_size": 8}


def _cfg(**over):
    d = dict(BASE)
    d.update(over)
    return DeepSpeedConfig(d, world_size=1)


# key -> (test value, expectation). Expectations:
#   ("attr", name, value)  config attribute takes the value
#   ("warn", substring)    diagnostic emitted containing substring
#   ("raise", exc)         parse rejects the value
# A key may map to a tuple of several (value, expectation) probes.
SWEEP = {
    "train_batch_size": (16, ("attr", "train_batch_size", 16)),
    "train_micro_batch_size_per_gpu": (4, ("attr", "train_micro_batch_size_per_gpu", 4)),
    "train_micro_batch_size_per_device": (4, ("attr", "train_micro_batch_size_per_gpu", 4)),
    "gradient_accumulation_steps": (2, ("attr", "gradient_accumulation_steps", 2)),
    "sparse_gradients": (True, ("attr", "sparse_gradients_enabled", True)),
    "optimizer": ({"type": "Lamb", "params": {"lr": 1e-3}},
                  ("attr", "optimizer_name", "lamb")),
    "scheduler": ({"type": "WarmupLR", "params": {}},
                  ("attr", "scheduler_name", "WarmupLR")),
    "fp16": ({"enabled": True, "loss_scale": 128}, ("attr", "loss_scale", 128)),
    "bf16": ({"enabled": False}, ("attr", "bf16_enabled", False)),
    "amp": ({"enabled": True, "opt_level": "O1"}, ("warn", "bf16")),
    "gradient_clipping": (1.0, ("attr", "gradient_clipping", 1.0)),
    "communication_data_type": (
        ("fp16", ("attr", "communication_data_type", "fp16")),
        ("int8", ("raise", ValueError)),
    ),
    "prescale_gradients": (True, ("attr", "prescale_gradients", True)),
    "fused_step": (True, ("attr", "fused_step", True)),
    "compilation_cache_dir": ("/tmp/xla-cache", ("attr", "compilation_cache_dir", "/tmp/xla-cache")),
    "gradient_predivide_factor": (2.0, ("attr", "gradient_predivide_factor", 2.0)),
    "disable_allgather": (True, ("warn", "no effect")),
    "allreduce_always_fp32": (True, ("attr", "allreduce_always_fp32", True)),
    "fp32_allreduce": (True, ("warn", "deprecated")),
    "steps_per_print": (5, ("attr", "steps_per_print", 5)),
    "dump_state": (True, ("attr", "dump_state", True)),
    "vocabulary_size": (1001, ("warn", "aligned")),
    "wall_clock_breakdown": (True, ("attr", "wall_clock_breakdown", True)),
    "memory_breakdown": (True, ("attr", "memory_breakdown", True)),
    "tensorboard": ({"enabled": True, "job_name": "j"},
                    ("attr", "tensorboard_job_name", "j")),
    "telemetry": (
        ({"enabled": True, "peak_tflops": 123.0}, ("attr", "telemetry_peak_tflops", 123.0)),
        ({"enabled": True, "trace_steps": [2, 5]},
         ("attr", "telemetry_trace_steps", (2, 5))),
        ({"enabled": True, "trace_steps": [5, 2]}, ("raise", ValueError)),
        ({"pipeline_trace": {"enabled": True, "capacity": 7}},
         ("attr", "pipeline_trace_capacity", 7)),
        ({"pipeline_trace": {"enabled": True, "dump_dir": "/tmp/pt"}},
         ("attr", "pipeline_trace_dump_dir", "/tmp/pt")),
        ({"pipeline_trace": {"enabled": True, "capacity": 0}}, ("raise", ValueError)),
        ({"anatomy": {"enabled": True}},
         ("attr", "telemetry_anatomy_enabled", True)),
        ({"anatomy": {"enabled": True, "chip": "tpu-v5e"}},
         ("attr", "telemetry_anatomy_chip", "tpu-v5e")),
        ({"anatomy": {"enabled": True, "peak_tflops": 275}},
         ("attr", "telemetry_anatomy_peak_tflops", 275.0)),
        ({"anatomy": {"enabled": True, "hbm_gbps": 819}},
         ("attr", "telemetry_anatomy_hbm_gbps", 819.0)),
        ({"anatomy": {"enabled": True, "ici_gbps": 200}},
         ("attr", "telemetry_anatomy_ici_gbps", 200.0)),
        ({"anatomy": {"enabled": True, "dcn_gbps": 25}},
         ("attr", "telemetry_anatomy_dcn_gbps", 25.0)),
        ({"anatomy": {"enabled": True, "peak_tflops": -1}}, ("raise", ValueError)),
        ({"anatomy": {"enabled": True, "hbm_gbps": True}}, ("raise", ValueError)),
        ({"enabled": True, "cluster": {"enabled": True}},
         ("attr", "telemetry_cluster_enabled", True)),
        ({"enabled": True, "cluster": {"enabled": True, "heartbeat_interval": 5}},
         ("attr", "telemetry_cluster_heartbeat_interval", 5)),
        ({"enabled": True, "cluster": {"enabled": True, "hang_deadline_s": 90}},
         ("attr", "telemetry_cluster_hang_deadline_s", 90.0)),
        ({"enabled": True, "cluster": {"enabled": True, "dump_dir": "/tmp/cl"}},
         ("attr", "telemetry_cluster_dump_dir", "/tmp/cl")),
        ({"enabled": True, "cluster": {"enabled": True, "straggler_threshold": 2.5}},
         ("attr", "telemetry_cluster_straggler_threshold", 2.5)),
        ({"enabled": True, "cluster": {"enabled": True, "signal_peers": False}},
         ("attr", "telemetry_cluster_signal_peers", False)),
        ({"enabled": True, "cluster": {"enabled": True, "warmup_steps": 3}},
         ("attr", "telemetry_cluster_warmup_steps", 3)),
        ({"enabled": True, "goodput": {"enabled": True}},
         ("attr", "telemetry_goodput_enabled", True)),
        ({"enabled": True, "goodput": {"enabled": True, "ledger_dir": "/tmp/gp"}},
         ("attr", "telemetry_goodput_ledger_dir", "/tmp/gp")),
        ({"enabled": True, "goodput": {"enabled": True, "emit_scalars": False}},
         ("attr", "telemetry_goodput_emit_scalars", False)),
        ({"enabled": True, "goodput": {"enabled": True, "eval_tag": "validation"}},
         ("attr", "telemetry_goodput_eval_tag", "validation")),
        # the ledger closes its step intervals on the telemetry end_step
        # record — no telemetry, no goodput
        ({"goodput": {"enabled": True}}, ("raise", ValueError)),
        ({"enabled": True, "goodput": {"enabled": True, "eval_tag": ""}},
         ("raise", ValueError)),
        ({"enabled": True, "goodput": {"enabled": True, "emit_scalars": 1}},
         ("raise", ValueError)),
        ({"enabled": True, "goodput": {"enabled": True, "ledger_dir": 5}},
         ("raise", ValueError)),
        ({"enabled": True, "profile": {"enabled": True}},
         ("attr", "telemetry_profile_enabled", True)),
        ({"enabled": True, "profile": {"enabled": True,
                                       "reconcile_tolerance": 0.1}},
         ("attr", "telemetry_profile_reconcile_tolerance", 0.1)),
        ({"enabled": True, "profile": {"enabled": True, "emit_scalars": False}},
         ("attr", "telemetry_profile_emit_scalars", False)),
        # the observatory ingests the trace window the telemetry session
        # writes — no telemetry, no profile
        ({"profile": {"enabled": True}}, ("raise", ValueError)),
        ({"enabled": True, "profile": {"enabled": True,
                                       "reconcile_tolerance": 0}},
         ("raise", ValueError)),
        ({"enabled": True, "profile": {"enabled": True, "emit_scalars": 1}},
         ("raise", ValueError)),
        ({"enabled": True, "profile": {"enabled": 1}}, ("raise", ValueError)),
        # the heartbeat rides the telemetry end_step record — no telemetry, no cluster
        ({"cluster": {"enabled": True}}, ("raise", ValueError)),
        ({"enabled": True, "cluster": {"enabled": True, "heartbeat_interval": 0}},
         ("raise", ValueError)),
        ({"enabled": True, "cluster": {"enabled": True, "hang_deadline_s": -1}},
         ("raise", ValueError)),
        ({"enabled": True, "cluster": {"enabled": True, "straggler_threshold": 1.0}},
         ("raise", ValueError)),
        ({"enabled": True, "cluster": {"enabled": True, "warmup_steps": -1}},
         ("raise", ValueError)),
        ({"enabled": True, "cluster": {"enabled": True, "warmup_steps": True}},
         ("raise", ValueError)),
        # metric catalog router (docs/metrics.md)
        ({"enabled": True, "metrics": {"enabled": True}},
         ("attr", "telemetry_metrics_enabled", True)),
        ({"enabled": True, "metrics": {"enabled": True, "ring_len": 128}},
         ("attr", "telemetry_metrics_ring_len", 128)),
        ({"enabled": True, "metrics": {"enabled": True,
                                       "strict_catalog": True}},
         ("attr", "telemetry_metrics_strict_catalog", True)),
        ({"enabled": True, "metrics": {"enabled": True,
                                       "export_path": "/tmp/om.txt"}},
         ("attr", "telemetry_metrics_export_path", "/tmp/om.txt")),
        # the router rides the monitor the telemetry session owns
        ({"metrics": {"enabled": True}}, ("raise", ValueError)),
        ({"enabled": True, "metrics": {"enabled": True, "ring_len": 0}},
         ("raise", ValueError)),
        ({"enabled": True, "metrics": {"enabled": True, "ring_len": True}},
         ("raise", ValueError)),
        ({"enabled": True, "metrics": {"enabled": True, "strict_catalog": 1}},
         ("raise", ValueError)),
        ({"enabled": True, "metrics": {"enabled": True, "export_path": 5}},
         ("raise", ValueError)),
        ({"enabled": True, "metrics": {"enabled": 1}}, ("raise", ValueError)),
        # alert plane (docs/alerts.md)
        ({"enabled": True, "alerts": {"enabled": True}},
         ("attr", "telemetry_alerts_enabled", True)),
        ({"enabled": True,
          "alerts": {"enabled": True,
                     "rules": [{"name": "hot", "kind": "threshold",
                                "metric": "Cluster/step_skew",
                                "above": 3.0}]}},
         ("attr", "telemetry_alerts_enabled", True)),
        # the rules evaluate on the end_step boundary telemetry drives
        ({"alerts": {"enabled": True}}, ("raise", ValueError)),
        ({"enabled": True, "alerts": {"enabled": 1}}, ("raise", ValueError)),
        ({"enabled": True, "alerts": {"enabled": True, "rules": "mfu"}},
         ("raise", ValueError)),
        ({"enabled": True,
          "alerts": {"enabled": True,
                     "rules": [{"name": "x", "kind": "gradient"}]}},
         ("raise", ValueError)),
        ({"enabled": True,
          "alerts": {"enabled": True,
                     "rules": [{"name": "x", "kind": "threshold",
                                "metric": "Bogus/metric", "above": 1}]}},
         ("raise", ValueError)),
    ),
    "numerics": (
        ({"enabled": True, "audit_interval": 7}, ("attr", "numerics_audit_interval", 7)),
        ({"enabled": True, "subtree_depth": 0}, ("raise", ValueError)),
        ({"enabled": True, "ring_size": 0}, ("raise", ValueError)),
    ),
    "serving": (
        ({"enabled": True, "block_size": 8, "max_model_len": 64},
         ("attr", "serving_block_size", 8)),
        ({"num_blocks": 1025}, ("attr", "serving_num_blocks", 1025)),
        ({"max_seqs": 16}, ("attr", "serving_max_seqs", 16)),
        ({"prefill_chunk": 64}, ("attr", "serving_prefill_chunk", 64)),
        ({"use_pallas_decode": True}, ("attr", "serving_use_pallas_decode", True)),
        ({"num_blocks": 1}, ("raise", ValueError)),     # no room for null page
        ({"block_size": 0}, ("raise", ValueError)),
        # paged gather bit-matches the oracle only when the tiling is exact
        ({"block_size": 16, "max_model_len": 100}, ("raise", ValueError)),
        ({"request_trace": {"enabled": True}},
         ("attr", "serving_request_trace_enabled", True)),
        ({"request_trace": {"enabled": True, "capacity": 33}},
         ("attr", "serving_request_trace_capacity", 33)),
        ({"request_trace": {"iteration_capacity": 99}},
         ("attr", "serving_request_trace_iteration_capacity", 99)),
        ({"request_trace": {"dump_dir": "/tmp/rt"}},
         ("attr", "serving_request_trace_dump_dir", "/tmp/rt")),
        ({"request_trace": {"slo": {"ttft_ms": 250.0}}},
         ("attr", "serving_slo_ttft_ms", 250.0)),
        ({"request_trace": {"slo": {"tpot_ms": 40}}},
         ("attr", "serving_slo_tpot_ms", 40.0)),
        ({"request_trace": {"capacity": 0}}, ("raise", ValueError)),
        ({"request_trace": {"iteration_capacity": 0}}, ("raise", ValueError)),
        ({"request_trace": {"slo": {"ttft_ms": -1}}}, ("raise", ValueError)),
        ({"request_trace": {"slo": {"tpot_ms": True}}}, ("raise", ValueError)),
        ({"sharding": {"model": 2}},
         ("attr", "serving_sharding_model", 2)),
        ({"sharding": {"model": 0}}, ("raise", ValueError)),
        ({"sharding": {"model": True}}, ("raise", ValueError)),
        ({"prefix_cache": {"enabled": True}},
         ("attr", "serving_prefix_cache_enabled", True)),
        ({"speculation": {"enabled": True}},
         ("attr", "serving_speculation_enabled", True)),
        ({"speculation": {"draft_model": "gpt2-124m"}},
         ("attr", "serving_speculation_draft_model", "gpt2-124m")),
        ({"speculation": {"max_draft_tokens": 6}},
         ("attr", "serving_speculation_max_draft_tokens", 6)),
        ({"speculation": {"draft_pool_blocks": 65}},
         ("attr", "serving_speculation_draft_pool_blocks", 65)),
        ({"speculation": {"max_draft_tokens": 0}}, ("raise", ValueError)),
        ({"speculation": {"max_draft_tokens": True}}, ("raise", ValueError)),
        # block 0 is the reserved null page: 1 usable block can't exist
        ({"speculation": {"draft_pool_blocks": 1}}, ("raise", ValueError)),
        ({"fleet": {"replicas": 3}},
         ("attr", "serving_fleet_replicas", 3)),
        ({"fleet": {"policy": "round_robin"}},
         ("attr", "serving_fleet_policy", "round_robin")),
        ({"fleet": {"affinity_weight": 2.5}},
         ("attr", "serving_fleet_affinity_weight", 2.5)),
        ({"fleet": {"max_queue_depth": 12}},
         ("attr", "serving_fleet_max_queue_depth", 12)),
        ({"fleet": {"occupancy_cap": 0.9}},
         ("attr", "serving_fleet_occupancy_cap", 0.9)),
        ({"fleet": {"goodput_floor": 0.85}},
         ("attr", "serving_fleet_goodput_floor", 0.85)),
        ({"fleet": {"replicas": 0}}, ("raise", ValueError)),
        ({"fleet": {"replicas": True}}, ("raise", ValueError)),
        ({"fleet": {"policy": "random"}}, ("raise", ValueError)),
        ({"fleet": {"affinity_weight": -1}}, ("raise", ValueError)),
        ({"fleet": {"max_queue_depth": -2}}, ("raise", ValueError)),
        ({"fleet": {"occupancy_cap": 0.0}}, ("raise", ValueError)),
        ({"fleet": {"occupancy_cap": 1.5}}, ("raise", ValueError)),
        ({"fleet": {"goodput_floor": 2.0}}, ("raise", ValueError)),
        ({"fleet": {"nonsense_key": 1}}, ("warn", "unknown serving.fleet")),
    ),
    "resilience": (
        ({"enabled": True, "save_dir": "/tmp/ckpt"},
         ("attr", "resilience_enabled", True)),
        ({"save_dir": "/tmp/ckpt"}, ("attr", "resilience_save_dir", "/tmp/ckpt")),
        ({"save_dir": "/tmp/ckpt", "save_interval": 50},
         ("attr", "resilience_save_interval", 50)),
        ({"async_save": False}, ("attr", "resilience_async_save", False)),
        ({"auto_resume": True}, ("attr", "resilience_auto_resume", True)),
        ({"save_interval": -1}, ("raise", ValueError)),
        ({"save_interval": True}, ("raise", ValueError)),
        # periodic saves with nowhere to put them is a config bug, not a no-op
        ({"enabled": True, "save_interval": 5}, ("raise", ValueError)),
        ({"nonsense_key": 1}, ("warn", "unknown resilience")),
    ),
    "comm": (
        ({"mode": "hierarchical"}, ("attr", "comm_mode", "hierarchical")),
        ({"mode": "hierarchical_compressed", "compress_start_step": 5},
         ("attr", "comm_compress_start_step", 5)),
        ({"dcn_slices": 2}, ("attr", "comm_dcn_slices", 2)),
        ({"mode": "ring"}, ("raise", ValueError)),
        ({"dcn_slices": -1}, ("raise", ValueError)),
        ({"compress_start_step": -3}, ("raise", ValueError)),
        ({"overlap": {"mode": "bucketed"}},
         ("attr", "comm_overlap_mode", "bucketed")),
        ({"overlap": {"mode": "bucketed", "bucket_mb": 12.5}},
         ("attr", "comm_overlap_bucket_mb", 12.5)),
        ({"overlap": {}}, ("attr", "comm_overlap_mode", "off")),
        ({"overlap": {"mode": "eager"}}, ("raise", ValueError)),
        ({"overlap": {"bucket_mb": 0}}, ("raise", ValueError)),
        ({"overlap": {"bucket_mb": True}}, ("raise", ValueError)),
    ),
    "sparse_attention": ({"mode": "fixed", "block": 16},
                         ("attr_pred", lambda c: c.sparse_attention.mode == "fixed")),
    "sequence_parallel": ({"enabled": True, "schedule": "masked"},
                          ("attr", "sequence_parallel_schedule", "masked")),
    "pipeline": ({"stages": 2}, ("attr_pred", lambda c: c.pipeline["stages"] == 2)),
    "zero_optimization": (
        ({"stage": 2}, ("attr", "zero_optimization_stage", 2)),
        ({"stage": 1, "overlap_comm": True}, ("warn", "no effect")),
        ({"stage": 1, "nonsense_key": 1}, ("warn", "unknown zero_optimization")),
        ({"stage": 1, "elastic_checkpoint": False}, ("warn", "elastic")),
    ),
    "zero_allow_untested_optimizer": (True, ("attr", "zero_allow_untested_optimizer", True)),
    "activation_checkpointing": (
        {"partition_activations": True},
        ("attr_pred", lambda c: c.activation_checkpointing_config.partition_activations)),
    # deprecated boolean-zero companion key: honored with {"zero_optimization": true}
    # (test_deprecated_boolean_zero_reads_allgather_size), warns otherwise
    "allgather_size": (500000000, ("warn", "only honored")),
}


def _run_probe(key, value, expect, capture):
    capture.records.clear()
    if expect[0] == "raise":
        with pytest.raises(expect[1]):
            _cfg(**{key: value})
        return
    cfg = _cfg(**{key: value})
    if expect[0] == "attr":
        assert getattr(cfg, expect[1]) == expect[2], key
    elif expect[0] == "attr_pred":
        assert expect[1](cfg), key
    elif expect[0] == "warn":
        assert expect[1] in capture.text, (key, capture.text)


@pytest.mark.parametrize("key", sorted(TOP_LEVEL_CONFIG_KEYS))
def test_every_registered_key_acts_or_diagnoses(key, capture):
    assert key in SWEEP, f"registry key {key!r} has no sweep probe — add one"
    probes = SWEEP[key]
    if not isinstance(probes[0], tuple):  # single (value, expect) pair
        probes = (probes,)
    for value, expect in probes:
        _run_probe(key, value, expect, capture)


def test_sweep_covers_exactly_the_registry():
    assert set(SWEEP) == set(TOP_LEVEL_CONFIG_KEYS)


def test_unknown_top_level_key_warns(capture):
    _cfg(definitely_not_a_key=1)
    assert "unknown top-level config key" in capture.text
    assert "definitely_not_a_key" in capture.text


def test_unknown_telemetry_key_warns(capture):
    _cfg(telemetry={"enabled": True, "trace_stepz": [2, 5]})
    assert "unknown telemetry config key" in capture.text
    assert "trace_stepz" in capture.text
    # the known-keys hint points at the fix
    assert "trace_steps" in capture.text


def test_unknown_pipeline_trace_key_warns(capture):
    _cfg(telemetry={"pipeline_trace": {"enabled": True, "capactiy": 7}})
    assert "unknown telemetry.pipeline_trace config key" in capture.text
    assert "capactiy" in capture.text


def test_unknown_anatomy_key_warns(capture):
    _cfg(telemetry={"anatomy": {"enabled": True, "chipp": "tpu-v4"}})
    assert "unknown telemetry.anatomy config key" in capture.text
    assert "chipp" in capture.text
    assert "chip" in capture.text    # the known-keys hint points at the fix


def test_unknown_profile_key_warns(capture):
    _cfg(telemetry={"enabled": True,
                    "profile": {"enabled": True, "tolernce": 0.1}})
    assert "unknown telemetry.profile config key" in capture.text
    assert "tolernce" in capture.text
    # the known-keys hint points at the fix
    assert "reconcile_tolerance" in capture.text


def test_unknown_metrics_key_warns(capture):
    _cfg(telemetry={"enabled": True,
                    "metrics": {"enabled": True, "ring_length": 128}})
    assert "unknown telemetry.metrics config key" in capture.text
    assert "ring_length" in capture.text
    assert "ring_len" in capture.text  # the known-keys hint points at the fix


def test_unknown_alerts_key_warns(capture):
    _cfg(telemetry={"enabled": True,
                    "alerts": {"enabled": True, "ruleset": []}})
    assert "unknown telemetry.alerts config key" in capture.text
    assert "ruleset" in capture.text
    assert "rules" in capture.text     # the known-keys hint points at the fix


def test_unknown_goodput_key_warns(capture):
    _cfg(telemetry={"enabled": True,
                    "goodput": {"enabled": True, "ledger_dirr": "/tmp/gp"}})
    assert "unknown telemetry.goodput config key" in capture.text
    assert "ledger_dirr" in capture.text
    assert "ledger_dir" in capture.text  # the known-keys hint points at the fix


def test_unknown_cluster_key_warns(capture):
    _cfg(telemetry={"enabled": True,
                    "cluster": {"enabled": True, "hang_deadline": 60}})
    assert "unknown telemetry.cluster config key" in capture.text
    assert "hang_deadline" in capture.text
    assert "hang_deadline_s" in capture.text  # the known-keys hint points at the fix


def test_unknown_serving_key_warns(capture):
    _cfg(serving={"enabled": True, "blok_size": 8})
    assert "unknown serving config key" in capture.text
    assert "blok_size" in capture.text


def test_unknown_request_trace_key_warns(capture):
    _cfg(serving={"request_trace": {"enabled": True, "capactiy": 7}})
    assert "unknown serving.request_trace config key" in capture.text
    assert "capactiy" in capture.text


def test_unknown_request_trace_slo_key_warns(capture):
    _cfg(serving={"request_trace": {"slo": {"ttft": 250.0}}})
    assert "unknown serving.request_trace.slo config key" in capture.text
    assert "ttft" in capture.text
    assert "ttft_ms" in capture.text     # the known-keys hint points at the fix


def test_unknown_serving_sharding_key_warns(capture):
    _cfg(serving={"sharding": {"model": 2, "modle": 4}})
    assert "unknown serving.sharding config key" in capture.text
    assert "modle" in capture.text
    assert "model" in capture.text       # the known-keys hint points at the fix


def test_unknown_prefix_cache_key_warns(capture):
    _cfg(serving={"prefix_cache": {"enabled": True, "enabeld": False}})
    assert "unknown serving.prefix_cache config key" in capture.text
    assert "enabeld" in capture.text
    assert "enabled" in capture.text     # the known-keys hint points at the fix


def test_unknown_speculation_key_warns(capture):
    _cfg(serving={"speculation": {"enabled": True, "max_draft_tokns": 4}})
    assert "unknown serving.speculation config key" in capture.text
    assert "max_draft_tokns" in capture.text
    assert "max_draft_tokens" in capture.text  # known-keys hint has the fix


def test_unknown_comm_key_warns(capture):
    _cfg(comm={"mode": "hierarchical", "dcn_slicez": 2})
    assert "unknown comm config key" in capture.text
    assert "dcn_slicez" in capture.text
    assert "dcn_slices" in capture.text  # the known-keys hint points at the fix


def test_unknown_comm_overlap_key_warns(capture):
    _cfg(comm={"overlap": {"mode": "bucketed", "bucket_md": 25}})
    assert "unknown comm.overlap config key" in capture.text
    assert "bucket_md" in capture.text
    assert "bucket_mb" in capture.text   # the known-keys hint points at the fix


def test_unknown_numerics_key_warns(capture):
    _cfg(numerics={"enabled": True, "ring_sz": 4})
    assert "unknown numerics config key" in capture.text
    assert "ring_sz" in capture.text


def test_known_nested_keys_do_not_warn(capture):
    _cfg(telemetry={"enabled": True, "trace_steps": [2, 5],
                    "pipeline_trace": {"enabled": True, "capacity": 7},
                    "anatomy": {"enabled": True, "chip": "tpu-v4",
                                "dcn_gbps": 25.0},
                    "goodput": {"enabled": True, "ledger_dir": "/tmp/gp",
                                "emit_scalars": True, "eval_tag": "eval"},
                    "profile": {"enabled": True, "reconcile_tolerance": 0.05,
                                "emit_scalars": True},
                    "metrics": {"enabled": True, "ring_len": 128,
                                "strict_catalog": True,
                                "export_path": "/tmp/om.txt"},
                    "alerts": {"enabled": True,
                               "rules": [{"name": "hot", "kind": "threshold",
                                          "metric": "Cluster/step_skew",
                                          "above": 3.0}]},
                    "cluster": {"enabled": True, "heartbeat_interval": 2,
                                "hang_deadline_s": 120.0, "dump_dir": "/tmp/cl",
                                "straggler_threshold": 3.0,
                                "signal_peers": True, "warmup_steps": 2}},
         numerics={"enabled": True, "audit_interval": 3},
         serving={"request_trace": {"enabled": True, "capacity": 64,
                                    "slo": {"ttft_ms": 250.0, "tpot_ms": 40.0}}},
         comm={"mode": "hierarchical", "dcn_slices": 2,
               "overlap": {"mode": "bucketed", "bucket_mb": 25.0}})
    assert "unknown" not in capture.text


def test_deprecated_boolean_zero_reads_allgather_size(capture):
    cfg = _cfg(zero_optimization=True, allgather_size=123456)
    assert cfg.zero_optimization_stage == 1
    assert cfg.zero_config.allgather_bucket_size == 123456
    assert "deprecated" in capture.text


def test_amp_plus_fp16_is_an_error():
    with pytest.raises(AssertionError, match="amp"):
        _cfg(amp={"enabled": True}, fp16={"enabled": True})


def test_amp_maps_to_bf16_policy(capture):
    cfg = _cfg(amp={"enabled": True}, bf16={"enabled": False})
    assert cfg.bf16_enabled  # amp overrides the explicit bf16 opt-out
    assert "bf16" in capture.text


def test_legacy_fusion_warns(capture):
    _cfg(optimizer={"type": "Adam", "params": {"lr": 1e-3}, "legacy_fusion": True})
    assert "legacy_fusion" in capture.text


def test_grad_comm_dtype_reaches_the_engine():
    """allreduce_always_fp32 / communication_data_type steer the dtype gradients
    are produced (and psum'd) in — reference engine.py:1016-1089."""
    import jax.numpy as jnp
    import deepspeed_tpu
    from simple_model import SimpleModel, simple_config

    def build(**over):
        model = SimpleModel(4)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(__import__("jax").random.PRNGKey(0)),
            config_params=simple_config(**over))
        return eng

    assert build(zero_optimization={"stage": 2})._grad_dtype == jnp.bfloat16
    assert build(zero_optimization={"stage": 2},
                 allreduce_always_fp32=True)._grad_dtype == jnp.float32
    assert build(communication_data_type="bf16")._grad_dtype == jnp.bfloat16
    assert build()._grad_dtype == jnp.float32


def test_untested_client_optimizer_under_zero_requires_opt_in():
    import jax
    import deepspeed_tpu
    from simple_model import SimpleModel, simple_config

    def init(params):
        return {}

    def apply(grads, opt_state, params, **kw):
        return params, opt_state

    model = SimpleModel(4)
    with pytest.raises(AssertionError, match="untested"):
        deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
            optimizer=(init, apply),
            config_params=simple_config(zero_optimization={"stage": 2}))
