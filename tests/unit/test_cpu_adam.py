"""CPU Adam + ZeRO-Offload tests (analog of reference tests/unit/test_cpu_adam.py and
the zero_stage x cpu_offload sweeps in tests/unit/test_fp16.py:236-301)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.ops import adam as jadam
from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam

from simple_model import SimpleModel, random_dataset, simple_config


def _params(rng):
    return {"w": rng.normal(size=(33, 17)).astype(np.float32),
            "b": rng.normal(size=(129,)).astype(np.float32)}


def test_cpu_adam_matches_fused_adam():
    """Trajectory parity vs the jitted fused Adam (mirrors test_cpu_adam.py's check
    against torch.optim.Adam)."""
    rng = np.random.default_rng(0)
    params = _params(rng)
    opt = DeepSpeedCPUAdam(params)
    jp = jax.tree_util.tree_map(jnp.asarray, params)
    jstate = jadam.init(jp)
    hyper = dict(lr=jnp.float32(1e-3), beta1=jnp.float32(0.9), beta2=jnp.float32(0.999),
                 eps=jnp.float32(1e-8), weight_decay=jnp.float32(0.01))
    for step in range(1, 8):
        g = _params(rng)
        opt.step(opt.flatten_grads(g), step=step, lr=1e-3, weight_decay=0.01)
        jp, jstate = jadam.apply(jax.tree_util.tree_map(jnp.asarray, g), jstate, jp,
                                 jnp.int32(step), hyper)
    got = opt.params_tree()
    for k in params:
        np.testing.assert_allclose(got[k], np.asarray(jp[k]), rtol=3e-5, atol=3e-6)


def _reference_adam_step(p, g, m, v, step, lr, b1, b2, eps, wd, adamw):
    """Hand-rolled fp64 oracle with torch semantics: torch.optim.Adam folds wd*p into
    the gradient BEFORE the moments (classic L2); torch.optim.AdamW decays p directly."""
    p, g, m, v = (np.asarray(a, np.float64) for a in (p, g, m, v))
    if not adamw:
        g = g + wd * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    update = (m / (1 - b1 ** step)) / (np.sqrt(v / (1 - b2 ** step)) + eps)
    p = p - lr * update - (lr * wd * p if adamw else 0.0)
    return p, m, v


@pytest.mark.parametrize("adamw", [False, True])
def test_adam_decay_semantics(adamw):
    """'type': 'Adam' must be classic L2 Adam (wd folded into the gradient before the
    moments, torch.optim.Adam semantics); 'AdamW' decoupled decay. Parity for the
    jitted fused path (ops/adam.py) and the host-tier DeepSpeedCPUAdam (native + numpy)
    vs a hand-rolled fp64 oracle — and vs torch itself when available.
    Reference update: csrc/adam/cpu_adam.cpp."""
    rng = np.random.default_rng(7)
    params = _params(rng)
    wd, lr = 0.1, 1e-2

    try:
        import torch
    except ImportError:
        torch = None
    if torch is not None:
        tparams = [torch.nn.Parameter(torch.from_numpy(params[k].copy()))
                   for k in sorted(params)]
        topt = (torch.optim.AdamW if adamw else torch.optim.Adam)(
            tparams, lr=lr, betas=(0.9, 0.999), eps=1e-8, weight_decay=wd)

    ref_p = {k: params[k].astype(np.float64) for k in params}
    ref_m = {k: np.zeros_like(ref_p[k]) for k in params}
    ref_v = {k: np.zeros_like(ref_p[k]) for k in params}

    jp = jax.tree_util.tree_map(jnp.asarray, params)
    jstate = jadam.init(jp)
    hyper = dict(lr=jnp.float32(lr), beta1=jnp.float32(0.9), beta2=jnp.float32(0.999),
                 eps=jnp.float32(1e-8), weight_decay=jnp.float32(wd))
    copt = DeepSpeedCPUAdam(params, adamw=adamw)
    nopt = DeepSpeedCPUAdam(params, adamw=adamw)
    nopt._lib = None  # numpy fallback path

    for step in range(1, 6):
        g = _params(rng)
        for k in params:
            ref_p[k], ref_m[k], ref_v[k] = _reference_adam_step(
                ref_p[k], g[k], ref_m[k], ref_v[k], step, lr, 0.9, 0.999, 1e-8, wd, adamw)
        if torch is not None:
            for tp, k in zip(tparams, sorted(params)):
                tp.grad = torch.from_numpy(g[k].copy())
            topt.step()
        jp, jstate = jadam.apply(jax.tree_util.tree_map(jnp.asarray, g), jstate, jp,
                                 jnp.int32(step), hyper, adamw=adamw)
        copt.step(copt.flatten_grads(g), step=step, lr=lr, weight_decay=wd)
        nopt.step(nopt.flatten_grads(g), step=step, lr=lr, weight_decay=wd)

    got_c, got_n = copt.params_tree(), nopt.params_tree()
    for k in params:
        np.testing.assert_allclose(np.asarray(jp[k]), ref_p[k], rtol=3e-5, atol=3e-6)
        np.testing.assert_allclose(got_c[k], ref_p[k], rtol=3e-5, atol=3e-6)
        np.testing.assert_allclose(got_n[k], ref_p[k], rtol=3e-5, atol=3e-6)
    if torch is not None:  # the oracle itself agrees with torch
        for tp, k in zip(tparams, sorted(params)):
            np.testing.assert_allclose(ref_p[k], tp.detach().numpy(), rtol=3e-5, atol=3e-6)


def test_cpu_adam_native_matches_numpy_fallback():
    rng = np.random.default_rng(1)
    params = _params(rng)
    a = DeepSpeedCPUAdam(params)
    b = DeepSpeedCPUAdam(params)
    b._lib = None  # force numpy path
    if a._lib is None:
        pytest.skip("native toolchain unavailable; fallback is the only path")
    for step in range(1, 5):
        g_flat = rng.normal(size=a.numel).astype(np.float32)
        a.step(g_flat, step=step, lr=1e-3, weight_decay=0.01)
        b.step(g_flat, step=step, lr=1e-3, weight_decay=0.01)
    np.testing.assert_allclose(a.fp32, b.fp32, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(a.exp_avg, b.exp_avg, rtol=1e-6, atol=1e-7)


def test_cpu_adam_fused_bf16_cast():
    rng = np.random.default_rng(2)
    opt = DeepSpeedCPUAdam(_params(rng))
    g = rng.normal(size=opt.numel).astype(np.float32)
    bf = opt.step_and_cast_bf16(g, step=1, lr=1e-2)
    assert bf.shape == (opt.numel,)
    np.testing.assert_allclose(np.asarray(bf, np.float32), opt.fp32, rtol=1e-2, atol=1e-2)


def _train(engine, steps=10, batch=8, hidden=16):
    data = random_dataset(batch * steps, hidden)
    losses = []
    for i in range(steps):
        xs = np.stack([data[i * batch + j][0] for j in range(batch)])
        ys = np.stack([data[i * batch + j][1] for j in range(batch)])
        loss = engine(xs, ys)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


@pytest.mark.parametrize("precision", ["bf16", "fp16"])
def test_engine_zero_offload_trains(precision):
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=8)
    cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    if precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    else:
        cfg["fp16"] = {"enabled": True, "loss_scale": 128.0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    assert engine._offload is not None
    losses = _train(engine, steps=30)
    assert losses[-1] < losses[0] * 0.7, f"loss did not drop: {losses[0]} -> {losses[-1]}"
    # master weights really live on host as numpy views of the flat buffer
    leaf = jax.tree_util.tree_leaves(engine.master_params)[0]
    assert isinstance(leaf, np.ndarray)
    assert leaf.base is engine._offload.fp32 or leaf.base.base is engine._offload.fp32


def test_engine_zero_offload_checkpoint_roundtrip(tmp_path):
    model = SimpleModel(hidden_dim=16)

    def make():
        params = model.init(jax.random.PRNGKey(0))
        cfg = simple_config(batch=8)
        cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
        cfg["bf16"] = {"enabled": True}
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                config_params=cfg)
        return eng

    e1 = make()
    _train(e1, steps=5)
    e1.save_checkpoint(str(tmp_path))
    e2 = make()
    e2.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(e2._offload.fp32, e1._offload.fp32, rtol=1e-6)
    np.testing.assert_allclose(e2._offload.exp_avg, e1._offload.exp_avg, rtol=1e-6)
    assert e2.global_steps == e1.global_steps
    # resumed training continues from identical state: next-step loss matches
    l1 = _train(e1, steps=1)[0]
    l2 = _train(e2, steps=1)[0]
    assert abs(l1 - l2) < 1e-5


def test_engine_zero_offload_fp16_overflow_skips_step():
    """Inf/NaN grads on the host tier must skip the master update and back off the loss
    scale (reference: CheckOverflow before DeepSpeedCPUAdam.step), not poison fp32."""
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=8)
    cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    cfg["fp16"] = {"enabled": True, "loss_scale": 0, "initial_scale_power": 4,
                   "hysteresis": 1}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    master_before = np.array(engine._offload.fp32, copy=True)
    s0 = float(engine.loss_scale())

    # SimpleModel computes in the input dtype, so fp32 math stays finite; the overflow
    # comes from the fp16 PARAM leaves: the huge target makes cotangents ~1e19, which
    # overflow when the grads are produced for the engine's fp16-stored params.
    x = np.ones((8, 16), np.float32)
    y = np.full((8, 16), 1e20, np.float32)
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()

    assert engine.skipped_steps == 1
    np.testing.assert_array_equal(engine._offload.fp32, master_before)
    assert np.all(np.isfinite(engine._offload.fp32))
    assert float(engine.loss_scale()) == s0 / 2, (s0, float(engine.loss_scale()))

    # and a sane batch afterwards still trains
    losses = _train(engine, steps=3)
    assert np.isfinite(losses).all()


def test_offload_partitioned_matches_device_engine():
    """Partitioned offload (real ZeRO regions over the 8-device mesh) must track the
    fully on-device ZeRO-2 engine: hidden_dim=64 makes the weight leaves big enough for
    zero_spec to shard them, so the host tier steps 8 distinct regions per leaf."""
    model = SimpleModel(hidden_dim=64)

    def make(offload):
        params = model.init(jax.random.PRNGKey(0))
        cfg = simple_config(batch=8)
        cfg["optimizer"] = {"type": "AdamW", "params": {"lr": 1e-2, "weight_decay": 0.01}}
        cfg["zero_optimization"] = {"stage": 2, "cpu_offload": offload}
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                config_params=cfg)
        return eng

    e_host, e_dev = make(True), make(False)
    if jax.device_count() > 1:
        # the host tier really is partitioned: >1 region for the sharded weight leaves
        assert any(len(r) > 1 for r in e_host._offload._leaf_regions)
    data = random_dataset(8 * 10, 64)
    for i in range(10):
        xs = np.stack([data[i * 8 + j][0] for j in range(8)])
        ys = np.stack([data[i * 8 + j][1] for j in range(8)])
        for eng in (e_host, e_dev):
            loss = eng(xs, ys)
            eng.backward(loss)
            eng.step()
    host_params = jax.device_get(e_host.params)
    dev_params = jax.device_get(e_dev.params)
    for k in host_params:
        # host (fma-ordered SIMD) vs XLA fused Adam drift compounds over 10 steps
        np.testing.assert_allclose(np.asarray(host_params[k], np.float32),
                                   np.asarray(dev_params[k], np.float32),
                                   rtol=1e-2, atol=1e-4)
    # master assembly agrees with the device master too
    host_master = e_host.master_params
    dev_master = jax.device_get(e_dev.master_params)
    for k in host_master:
        np.testing.assert_allclose(host_master[k], np.asarray(dev_master[k]),
                                   rtol=1e-2, atol=1e-4)
    t = e_host._offload.last_step_timing
    assert t is not None and t["total"] > 0


def test_region_layout_non_contiguous_assembly():
    """A leaf sharded on a non-leading axis stores non-contiguous regions; assembly and
    load_trees must still round-trip exactly."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs).reshape(len(devs), 1), ("data", "model"))
    rng = np.random.default_rng(3)
    params = {"w": rng.normal(size=(24, 8 * len(devs))).astype(np.float32)}
    shard = {"w": NamedSharding(mesh, P(None, "data"))}  # axis-1: non-contiguous regions
    opt = DeepSpeedCPUAdam(params, shardings=shard)
    assert not opt._leaf_viewable[0]
    got = opt.params_tree()
    np.testing.assert_array_equal(got["w"], params["w"])
    # round-trip through load_trees
    new = {"w": rng.normal(size=params["w"].shape).astype(np.float32)}
    opt.load_trees(master_tree=new)
    np.testing.assert_array_equal(opt.params_tree()["w"], new["w"])
    # a flat-buffer step over regions equals a whole-tree step
    ref = DeepSpeedCPUAdam(params)
    g = {"w": rng.normal(size=params["w"].shape).astype(np.float32)}
    opt.load_trees(master_tree=params)
    opt.step(opt.flatten_grads(g), step=1, lr=1e-2, weight_decay=0.01)
    ref.step(ref.flatten_grads(g), step=1, lr=1e-2, weight_decay=0.01)
    np.testing.assert_allclose(opt.params_tree()["w"], ref.params_tree()["w"],
                               rtol=1e-6, atol=1e-7)


def _make_engine(model, offload, lr=1e-2):
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=8)
    cfg["optimizer"] = {"type": "AdamW", "params": {"lr": lr, "weight_decay": 0.01}}
    cfg["zero_optimization"] = {"stage": 2, "cpu_offload": offload}
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                            config_params=cfg)
    return eng


def test_offload_region_checkpoint_partitioned_roundtrip(tmp_path):
    """Region-wise offload checkpoint (per-process files) with REAL ZeRO partitions
    (hidden 64 -> sharded leaves): save -> fresh engine load -> identical buffers and
    identical next-step loss."""
    model = SimpleModel(hidden_dim=64)
    e1 = _make_engine(model, offload=True)
    assert any(len(r) > 1 for r in e1._offload._leaf_regions)
    _train(e1, steps=5, hidden=64)
    e1.save_checkpoint(str(tmp_path))
    import os
    assert os.path.isfile(tmp_path / f"global_step{e1.global_steps}" /
                          "offload_manifest_0.json")
    e2 = _make_engine(model, offload=True)
    e2.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(e2._offload.fp32, e1._offload.fp32, rtol=1e-6)
    np.testing.assert_allclose(e2._offload.exp_avg, e1._offload.exp_avg, rtol=1e-6)
    l1 = _train(e1, steps=1, hidden=64)[0]
    l2 = _train(e2, steps=1, hidden=64)[0]
    assert abs(l1 - l2) < 1e-5


def test_offload_checkpoint_cross_layout(tmp_path):
    """An offload (region-layout) checkpoint must restore into a NON-offload engine and
    vice versa — the loader detects the on-disk layout, not the engine mode."""
    model = SimpleModel(hidden_dim=64)
    # offload save -> device-engine load
    e1 = _make_engine(model, offload=True)
    _train(e1, steps=4, hidden=64)
    e1.save_checkpoint(str(tmp_path / "a"))
    e2 = _make_engine(model, offload=False)
    e2.load_checkpoint(str(tmp_path / "a"))
    m1 = e1.master_params
    m2 = jax.device_get(e2.master_params)
    for k in m1:
        np.testing.assert_allclose(np.asarray(m2[k]), m1[k], rtol=1e-6, atol=1e-7)
    l1 = _train(e1, steps=1, hidden=64)[0]
    l2 = _train(e2, steps=1, hidden=64)[0]
    assert abs(l1 - l2) < 1e-4

    # device-engine save -> offload load
    e3 = _make_engine(model, offload=False)
    _train(e3, steps=4, hidden=64)
    e3.save_checkpoint(str(tmp_path / "b"))
    e4 = _make_engine(model, offload=True)
    e4.load_checkpoint(str(tmp_path / "b"))
    m3 = jax.device_get(e3.master_params)
    m4 = e4.master_params
    for k in m4:
        np.testing.assert_allclose(m4[k], np.asarray(m3[k]), rtol=1e-6, atol=1e-7)
    l3 = _train(e3, steps=1, hidden=64)[0]
    l4 = _train(e4, steps=1, hidden=64)[0]
    assert abs(l3 - l4) < 1e-4


def test_offload_push_bytes_proportional_to_partition():
    """H2D pushes after the host step must total the local PARTITION size, not
    x n_devices (VERDICT r2 next #9): replicated leaves ride one PCIe push + an
    on-device broadcast."""
    model = SimpleModel(hidden_dim=16)  # leaves too small to shard -> replicated on 8 devs
    eng = _make_engine(model, offload=True)
    _train(eng, steps=1)
    off = eng._offload
    assert off.last_push_elements == off.numel, \
        (off.last_push_elements, off.numel, jax.device_count())
    if jax.device_count() > 1:
        # every region in this config is replicated across all devices
        assert all(len(r.devices or []) > 1 for rs in off._leaf_regions for r in rs)
    # the broadcast arrays still carry the construction shardings
    for leaf, sh in zip(jax.tree_util.tree_leaves(eng.params),
                        jax.tree_util.tree_leaves(eng._param_shardings)):
        assert leaf.sharding.is_equivalent_to(sh, leaf.ndim)


def test_offload_grad_fetch_fallback_uses_addressable_shards():
    """A grad layout that doesn't tile the master regions must be assembled from
    addressable shards (never whole-leaf device_get, which breaks multi-host), and the
    stepped result must match the matched-layout path (ADVICE r2 medium #2)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs).reshape(len(devs), 1), ("data", "model"))
    rng = np.random.default_rng(11)
    params = {"w": rng.normal(size=(8 * len(devs), 16)).astype(np.float32)}
    master_sh = {"w": NamedSharding(mesh, P("data", None))}
    opt = DeepSpeedCPUAdam(params, shardings=master_sh)
    assert len(opt._leaf_regions[0]) == len(devs)

    g_np = {"w": rng.normal(size=params["w"].shape).astype(np.float32)}
    # grads sharded on the WRONG axis: per-device shard shape != region shape
    g_dev = {"w": jax.device_put(g_np["w"], NamedSharding(mesh, P(None, "data")))}
    handles = opt.begin_grad_fetch(g_dev)
    assert any(kind == "region_shards" for kind, *_ in handles)
    assert opt._warned_fallback
    opt.step_regions(handles, step=1, lr=1e-2, weight_decay=0.01)

    ref = DeepSpeedCPUAdam(params, shardings=master_sh)
    ref.step_regions(ref.begin_grad_fetch(
        {"w": jax.device_put(g_np["w"], master_sh["w"])}), step=1, lr=1e-2,
        weight_decay=0.01)
    np.testing.assert_allclose(opt.fp32, ref.fp32, rtol=1e-6, atol=1e-7)


def test_offload_grad_accumulation_fp32_accumulator():
    """With accumulation > 1 under offload, the accumulate buffer must be fp32 even
    though per-microbatch grads stay in the compute dtype (ADVICE r2 medium #1)."""
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=16, gradient_accumulation_steps=2)
    cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    cfg["bf16"] = {"enabled": True}
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                            config_params=cfg)
    assert eng._acc_dtype == jnp.float32
    x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
    y = np.zeros((8, 16), np.float32)
    loss = eng(x, y)
    eng.backward(loss)
    for leaf in jax.tree_util.tree_leaves(eng._grad_acc):
        assert leaf.dtype == jnp.float32
    loss = eng(x, y)
    eng.backward(loss)
    eng.step()
    assert eng.global_steps == 1
    assert np.all(np.isfinite(eng._offload.fp32))
