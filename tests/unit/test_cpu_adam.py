"""CPU Adam + ZeRO-Offload tests (analog of reference tests/unit/test_cpu_adam.py and
the zero_stage x cpu_offload sweeps in tests/unit/test_fp16.py:236-301)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.ops import adam as jadam
from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam

from simple_model import SimpleModel, random_dataset, simple_config


def _params(rng):
    return {"w": rng.normal(size=(33, 17)).astype(np.float32),
            "b": rng.normal(size=(129,)).astype(np.float32)}


def test_cpu_adam_matches_fused_adam():
    """Trajectory parity vs the jitted fused Adam (mirrors test_cpu_adam.py's check
    against torch.optim.Adam)."""
    rng = np.random.default_rng(0)
    params = _params(rng)
    opt = DeepSpeedCPUAdam(params)
    jp = jax.tree_util.tree_map(jnp.asarray, params)
    jstate = jadam.init(jp)
    hyper = dict(lr=jnp.float32(1e-3), beta1=jnp.float32(0.9), beta2=jnp.float32(0.999),
                 eps=jnp.float32(1e-8), weight_decay=jnp.float32(0.01))
    for step in range(1, 8):
        g = _params(rng)
        opt.step(opt.flatten_grads(g), step=step, lr=1e-3, weight_decay=0.01)
        jp, jstate = jadam.apply(jax.tree_util.tree_map(jnp.asarray, g), jstate, jp,
                                 jnp.int32(step), hyper)
    got = opt.params_tree()
    for k in params:
        np.testing.assert_allclose(got[k], np.asarray(jp[k]), rtol=3e-5, atol=3e-6)


def test_cpu_adam_native_matches_numpy_fallback():
    rng = np.random.default_rng(1)
    params = _params(rng)
    a = DeepSpeedCPUAdam(params)
    b = DeepSpeedCPUAdam(params)
    b._lib = None  # force numpy path
    if a._lib is None:
        pytest.skip("native toolchain unavailable; fallback is the only path")
    for step in range(1, 5):
        g_flat = rng.normal(size=a.numel).astype(np.float32)
        a.step(g_flat, step=step, lr=1e-3, weight_decay=0.01)
        b.step(g_flat, step=step, lr=1e-3, weight_decay=0.01)
    np.testing.assert_allclose(a.fp32, b.fp32, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(a.exp_avg, b.exp_avg, rtol=1e-6, atol=1e-7)


def test_cpu_adam_fused_bf16_cast():
    rng = np.random.default_rng(2)
    opt = DeepSpeedCPUAdam(_params(rng))
    g = rng.normal(size=opt.numel).astype(np.float32)
    bf = opt.step_and_cast_bf16(g, step=1, lr=1e-2)
    assert bf.shape == (opt.numel,)
    np.testing.assert_allclose(np.asarray(bf, np.float32), opt.fp32, rtol=1e-2, atol=1e-2)


def _train(engine, steps=10, batch=8, hidden=16):
    data = random_dataset(batch * steps, hidden)
    losses = []
    for i in range(steps):
        xs = np.stack([data[i * batch + j][0] for j in range(batch)])
        ys = np.stack([data[i * batch + j][1] for j in range(batch)])
        loss = engine(xs, ys)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


@pytest.mark.parametrize("precision", ["bf16", "fp16"])
def test_engine_zero_offload_trains(precision):
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=8)
    cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    if precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    else:
        cfg["fp16"] = {"enabled": True, "loss_scale": 128.0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    assert engine._offload is not None
    losses = _train(engine, steps=30)
    assert losses[-1] < losses[0] * 0.7, f"loss did not drop: {losses[0]} -> {losses[-1]}"
    # master weights really live on host as numpy views of the flat buffer
    leaf = jax.tree_util.tree_leaves(engine.master_params)[0]
    assert isinstance(leaf, np.ndarray)
    assert leaf.base is engine._offload.fp32 or leaf.base.base is engine._offload.fp32


def test_engine_zero_offload_checkpoint_roundtrip(tmp_path):
    model = SimpleModel(hidden_dim=16)

    def make():
        params = model.init(jax.random.PRNGKey(0))
        cfg = simple_config(batch=8)
        cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
        cfg["bf16"] = {"enabled": True}
        eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                config_params=cfg)
        return eng

    e1 = make()
    _train(e1, steps=5)
    e1.save_checkpoint(str(tmp_path))
    e2 = make()
    e2.load_checkpoint(str(tmp_path))
    np.testing.assert_allclose(e2._offload.fp32, e1._offload.fp32, rtol=1e-6)
    np.testing.assert_allclose(e2._offload.exp_avg, e1._offload.exp_avg, rtol=1e-6)
    assert e2.global_steps == e1.global_steps
    # resumed training continues from identical state: next-step loss matches
    l1 = _train(e1, steps=1)[0]
    l2 = _train(e2, steps=1)[0]
    assert abs(l1 - l2) < 1e-5


def test_engine_zero_offload_fp16_overflow_skips_step():
    """Inf/NaN grads on the host tier must skip the master update and back off the loss
    scale (reference: CheckOverflow before DeepSpeedCPUAdam.step), not poison fp32."""
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=8)
    cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    cfg["fp16"] = {"enabled": True, "loss_scale": 0, "initial_scale_power": 4,
                   "hysteresis": 1}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    master_before = np.array(engine._offload.fp32, copy=True)
    s0 = float(engine.loss_scale())

    # SimpleModel computes in the input dtype, so fp32 math stays finite; the overflow
    # comes from the fp16 PARAM leaves: the huge target makes cotangents ~1e19, which
    # overflow when the grads are produced for the engine's fp16-stored params.
    x = np.ones((8, 16), np.float32)
    y = np.full((8, 16), 1e20, np.float32)
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()

    assert engine.skipped_steps == 1
    np.testing.assert_array_equal(engine._offload.fp32, master_before)
    assert np.all(np.isfinite(engine._offload.fp32))
    assert float(engine.loss_scale()) == s0 / 2, (s0, float(engine.loss_scale()))

    # and a sane batch afterwards still trains
    losses = _train(engine, steps=3)
    assert np.isfinite(losses).all()
