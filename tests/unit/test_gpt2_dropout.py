"""GPT-2 stateless dropout: explicit PRNG keys replace the reference's CUDA RNG state
tracker (checkpointing.py:147-262) — identical masks under remat recompute for free."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model


def _setup(dropout, remat=False):
    cfg = GPT2Config(vocab_size=64, n_positions=16, n_embd=32, n_layer=2, n_head=2,
                     dropout=dropout, remat=remat, compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = np.random.default_rng(0).integers(0, 64, (4, 16)).astype(np.int32)
    labels = np.roll(toks, -1, 1)
    return model, params, toks, labels


def test_no_rng_is_deterministic_eval():
    model, params, toks, labels = _setup(dropout=0.5)
    a = float(model.apply(params, toks, labels))
    b = float(model.apply(params, toks, labels))
    assert a == b


def test_dropout_changes_with_key_and_reproduces_with_same_key():
    model, params, toks, labels = _setup(dropout=0.5)
    base = float(model.apply(params, toks, labels))
    l1 = float(model.apply(params, toks, labels, rng=jax.random.PRNGKey(1)))
    l2 = float(model.apply(params, toks, labels, rng=jax.random.PRNGKey(2)))
    l1_again = float(model.apply(params, toks, labels, rng=jax.random.PRNGKey(1)))
    assert l1 != l2 and l1 != base, (base, l1, l2)
    assert l1 == l1_again, "same key must reproduce the same masks"


def test_zero_rate_with_rng_matches_eval():
    model, params, toks, labels = _setup(dropout=0.0)
    a = float(model.apply(params, toks, labels))
    b = float(model.apply(params, toks, labels, rng=jax.random.PRNGKey(3)))
    assert a == b


def test_dropout_grads_under_remat_match_no_remat():
    """Remat recomputes the blocks in backward; the threaded keys must yield identical
    masks so grads match the no-remat run exactly."""
    m_plain, params, toks, labels = _setup(dropout=0.3, remat=False)
    m_remat, _, _, _ = _setup(dropout=0.3, remat=True)
    key = jax.random.PRNGKey(7)
    g1 = jax.grad(lambda p: m_plain.apply(p, toks, labels, rng=key))(params)
    g2 = jax.grad(lambda p: m_remat.apply(p, toks, labels, rng=key))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)
