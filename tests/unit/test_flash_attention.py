"""Flash-attention kernel parity tests (interpret mode on CPU; compiled path covered by
bench/TPU runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.flash_attention import flash_attention, dense_attention


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 3, 256, 64), (1, 2, 128, 32)])
def test_forward_parity(causal, shape):
    B, H, T, D = shape
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, causal, None, 128, 128, True)
    out_d = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_parity(causal):
    shape = (2, 3, 256, 64)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    g = jax.random.normal(jax.random.PRNGKey(9), shape)
    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal, None, 128, 128, True) * g),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=causal) * g),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name}")


def test_block_size_autofit():
    # T=192 is not divisible by the default blocks; the kernel must fit them down
    shape = (1, 2, 192, 32)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, True, None, 256, 512, True)
    out_d = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5)


def test_sm_scale_override():
    shape = (1, 2, 128, 32)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, False, 0.5, 128, 128, True)
    out_d = dense_attention(q, k, v, causal=False, sm_scale=0.5)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5)
