"""Flash-attention kernel parity tests (interpret mode on CPU; compiled path covered by
bench/TPU runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.flash_attention import flash_attention, dense_attention


# bf16 exercises the kernels' MXU-native cast paths (bf16 operands, fp32 accumulate);
# fp32 pins exact numerics. Tolerances scale with the dtype's epsilon.
_DTYPES = [(jnp.float32, 2e-5, 2e-4), (jnp.bfloat16, 3e-2, 5e-2)]


@pytest.mark.parametrize("dtype,fwd_tol,bwd_tol", _DTYPES)
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 3, 256, 64), (1, 2, 128, 32)])
def test_forward_parity(causal, shape, dtype, fwd_tol, bwd_tol):
    B, H, T, D = shape
    q, k, v = (jax.random.normal(kk, shape, jnp.float32).astype(dtype)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, causal, None, 128, 128, True).astype(jnp.float32)
    # reference in fp32 regardless of input dtype
    out_d = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                            v.astype(jnp.float32), causal=causal)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=fwd_tol, atol=fwd_tol)


@pytest.mark.parametrize("dtype,fwd_tol,bwd_tol", _DTYPES)
@pytest.mark.parametrize("causal", [False, True])
def test_backward_parity(causal, dtype, fwd_tol, bwd_tol):
    shape = (2, 3, 256, 64)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32).astype(dtype)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    g = jax.random.normal(jax.random.PRNGKey(9), shape, jnp.float32).astype(dtype)
    gf = jax.grad(lambda q, k, v: jnp.sum((flash_attention(q, k, v, causal, None, 128, 128, True)
                                           * g).astype(jnp.float32)),
                  argnums=(0, 1, 2))(q, k, v)
    f32 = (q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=causal)
                                          * g.astype(jnp.float32)),
                  argnums=(0, 1, 2))(*f32)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b),
                                   rtol=bwd_tol, atol=bwd_tol, err_msg=f"d{name}")


def test_block_size_autofit():
    # T=192 is not divisible by the default blocks; the kernel must fit them down
    shape = (1, 2, 192, 32)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, True, None, 256, 512, True)
    out_d = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5)


def test_sm_scale_override():
    shape = (1, 2, 128, 32)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, False, 0.5, 128, 128, True)
    out_d = dense_attention(q, k, v, causal=False, sm_scale=0.5)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_bias_mask_parity(causal):
    """Additive key bias (the BERT padding mask) fused in-kernel must match the dense
    oracle in forward and all three gradients."""
    B, H, T, D = 2, 3, 128, 32
    q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(1), 3))
    bias = np.zeros((B, 1, 1, T), np.float32)
    # padding sits at the END of the sequence (BERT convention) so no causal row is
    # fully masked — a fully-masked row's softmax is degenerate/undefined
    bias[0, ..., -17:] = -1e9
    bias[1, ..., -5:] = -1e9
    bias = jnp.asarray(bias)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, 128, 128, True,
                                       bias=bias) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal,
                                       bias=bias.reshape(B, 1, T)) ** 2)

    np.testing.assert_allclose(float(f_flash(q, k, v)), float(f_dense(q, k, v)),
                               rtol=2e-5)
    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_dropout_parity_vs_oracle(causal):
    """In-kernel dropout must equal dense attention with the exact oracle keep-mask
    (dropout_keep_reference reproduces the kernel's coordinate-hash bit stream), in
    forward AND gradients — this pins fwd/bwd mask agreement across all three kernels."""
    from deepspeed_tpu.ops.pallas.flash_attention import dropout_keep_reference
    B, H, T, D = 2, 2, 128, 32
    rate, seed = 0.15, 4242
    q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(2), 3))
    keep = dropout_keep_reference(seed, B, H, T, T, rate)
    # the mask really drops ~rate of entries and scales the rest
    frac = float((keep == 0).mean())
    assert abs(frac - rate) < 0.02, frac

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, 64, 64, True,
                                       dropout_rate=rate, dropout_seed=seed) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal, dropout_keep=keep) ** 2)

    np.testing.assert_allclose(float(f_flash(q, k, v)), float(f_dense(q, k, v)),
                               rtol=2e-5)
    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name}")


def test_dropout_block_shape_invariance():
    """The coordinate-hash mask must not depend on block configuration (this is what
    guarantees fwd/bwd agreement when block_q != block_k)."""
    B, H, T, D = 1, 2, 256, 32
    q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(3), 3))
    o1 = flash_attention(q, k, v, False, None, 64, 128, True,
                         dropout_rate=0.1, dropout_seed=7)
    o2 = flash_attention(q, k, v, False, None, 256, 64, True,
                         dropout_rate=0.1, dropout_seed=7)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-5, atol=1e-5)
    o3 = flash_attention(q, k, v, False, None, 64, 128, True,
                         dropout_rate=0.1, dropout_seed=8)
    assert np.abs(np.asarray(o1) - np.asarray(o3)).max() > 1e-3  # seed actually matters


def test_transformer_layer_masked_dropout_uses_flash(monkeypatch):
    """DeepSpeedTransformerLayer with an attention_mask AND train-mode attn dropout must
    dispatch to the flash kernel (VERDICT: the BERT pretraining path stayed dense)."""
    from deepspeed_tpu.ops.transformer.transformer import (DeepSpeedTransformerConfig,
                                                           DeepSpeedTransformerLayer)
    import importlib
    fa = importlib.import_module("deepspeed_tpu.ops.pallas.flash_attention")
    calls = {"n": 0}
    real = fa.flash_attention

    def spy(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(fa, "flash_attention", spy)
    cfg = DeepSpeedTransformerConfig(batch_size=2, max_seq_length=64, hidden_size=64,
                                     heads=4, attn_dropout_ratio=0.1,
                                     hidden_dropout_ratio=0.0, num_hidden_layers=2,
                                     initializer_range=0.02, bf16=False)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64), jnp.float32)
    mask = np.zeros((2, 1, 1, 64), np.float32)
    mask[:, ..., -8:] = -1e9
    out = layer.apply(params, x, attention_mask=jnp.asarray(mask),
                      rng=jax.random.PRNGKey(2), deterministic=False)
    assert calls["n"] == 1, "masked+dropout attention did not dispatch to flash"
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize(
    "causal", [pytest.param(False, marks=pytest.mark.slow), True])
def test_chunked_long_context_matches_dense(causal):
    """The k-chunked long-context path (used past the resident kernel's VMEM cap)
    must match dense attention exactly — fwd and grads, causal decomposition
    included (diagonal square + trailing rectangles)."""
    from deepspeed_tpu.ops.pallas.flash_attention import _flash_attention_chunked

    B, H, T, D = 1, 2, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.float32) for kk in ks)
    out = _flash_attention_chunked(q, k, v, causal, None, True, chunk=64)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    g = jax.random.normal(jax.random.PRNGKey(12), (B, H, T, D), jnp.float32)
    gc = jax.grad(lambda q, k, v: jnp.sum(_flash_attention_chunked(
        q, k, v, causal, None, True, chunk=64) * g), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_attention(
        q, k, v, causal=causal) * g), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gc, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                                   err_msg=f"d{n} (causal={causal})")


@pytest.mark.slow  # whole-sequence oracle mask, compile-bound (~33s for the pair)
@pytest.mark.parametrize("causal", [False, True])
def test_chunked_dropout_matches_global_oracle(causal):
    """Chunked tiles hash GLOBAL coordinates: dropout through the chunked path must
    equal dense attention with the whole-sequence oracle mask (VERDICT r3 #4 — the
    long-context path previously ran without attention dropout)."""
    from deepspeed_tpu.ops.pallas.flash_attention import (_flash_attention_chunked,
                                                          dropout_keep_reference)
    B, H, T, D = 1, 2, 256, 32
    rate, seed = 0.15, 99
    ks = jax.random.split(jax.random.PRNGKey(21), 3)
    q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.float32) for kk in ks)
    keep = dropout_keep_reference(seed, B, H, T, T, rate)

    def f_chunk(q, k, v):
        return jnp.sum(_flash_attention_chunked(q, k, v, causal, None, True,
                                                chunk=64, rate=rate, seed=seed) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal, dropout_keep=keep) ** 2)

    np.testing.assert_allclose(float(f_chunk(q, k, v)), float(f_dense(q, k, v)),
                               rtol=2e-5)
    gc = jax.grad(f_chunk, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gc, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                                   err_msg=f"d{n} (causal={causal})")


def test_long_context_dispatch_raises_when_chunk_ineligible(monkeypatch):
    """Past the resident VMEM ceiling, an ineligible chunked path must raise a
    descriptive error instead of compiling the resident kernel into a Mosaic
    failure (ADVICE r3)."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    D = 32
    k = jnp.zeros((1, 1, 16384, D), jnp.bfloat16)
    v = jnp.zeros((1, 1, 16384, D), jnp.bfloat16)
    # non-square cross attention
    with pytest.raises(ValueError, match="square self-attention"):
        flash_attention(jnp.zeros((1, 1, 128, D), jnp.bfloat16), k, v)
    # additive bias not supported on the chunked path
    q = jnp.zeros((1, 1, 16384, D), jnp.bfloat16)
    with pytest.raises(ValueError, match="additive bias"):
        flash_attention(q, k, v, bias=jnp.zeros((1, 1, 1, 16384)))
    # no divisor chunk >= 1024 (8704 = 512 * 17)
    t = jnp.zeros((1, 1, 8704, D), jnp.bfloat16)
    with pytest.raises(ValueError, match="divisor"):
        flash_attention(t, t, t)
