"""Flash-attention kernel parity tests (interpret mode on CPU; compiled path covered by
bench/TPU runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.flash_attention import flash_attention, dense_attention


# bf16 exercises the kernels' MXU-native cast paths (bf16 operands, fp32 accumulate);
# fp32 pins exact numerics. Tolerances scale with the dtype's epsilon.
_DTYPES = [(jnp.float32, 2e-5, 2e-4), (jnp.bfloat16, 3e-2, 5e-2)]


@pytest.mark.parametrize("dtype,fwd_tol,bwd_tol", _DTYPES)
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 3, 256, 64), (1, 2, 128, 32)])
def test_forward_parity(causal, shape, dtype, fwd_tol, bwd_tol):
    B, H, T, D = shape
    q, k, v = (jax.random.normal(kk, shape, jnp.float32).astype(dtype)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, causal, None, 128, 128, True).astype(jnp.float32)
    # reference in fp32 regardless of input dtype
    out_d = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                            v.astype(jnp.float32), causal=causal)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=fwd_tol, atol=fwd_tol)


@pytest.mark.parametrize("dtype,fwd_tol,bwd_tol", _DTYPES)
@pytest.mark.parametrize("causal", [False, True])
def test_backward_parity(causal, dtype, fwd_tol, bwd_tol):
    shape = (2, 3, 256, 64)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32).astype(dtype)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    g = jax.random.normal(jax.random.PRNGKey(9), shape, jnp.float32).astype(dtype)
    gf = jax.grad(lambda q, k, v: jnp.sum((flash_attention(q, k, v, causal, None, 128, 128, True)
                                           * g).astype(jnp.float32)),
                  argnums=(0, 1, 2))(q, k, v)
    f32 = (q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    gd = jax.grad(lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=causal)
                                          * g.astype(jnp.float32)),
                  argnums=(0, 1, 2))(*f32)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b),
                                   rtol=bwd_tol, atol=bwd_tol, err_msg=f"d{name}")


def test_block_size_autofit():
    # T=192 is not divisible by the default blocks; the kernel must fit them down
    shape = (1, 2, 192, 32)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, True, None, 256, 512, True)
    out_d = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5)


def test_sm_scale_override():
    shape = (1, 2, 128, 32)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(jax.random.PRNGKey(0), 3))
    out_f = flash_attention(q, k, v, False, 0.5, 128, 128, True)
    out_d = dense_attention(q, k, v, causal=False, sm_scale=0.5)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5)
