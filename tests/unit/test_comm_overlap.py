"""Overlap-centric grad exchange tests (docs/overlap.md).

Covers the ISSUE-11 acceptance contract: deterministic bucket partition at a
given ``comm.overlap.bucket_mb``, bit-equality of the bucketed exchange
against the monolithic exchange across the engine's step paths (two-jit
standard, fused standard, fused external-master, two-jit compressed), the
bucketed error-feedback state layout, and HLO-instruction-identical steps
when ``comm.overlap`` is off.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm import CommTopology
from deepspeed_tpu.comm.hierarchical import (bucket_partition, bucket_plan,
                                             bucketed_error_state_shapes,
                                             error_state_shapes)
from deepspeed_tpu.utils.hlo import optimized_hlo
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 64

# tiny buckets: SimpleModel(64) splits into (b1, b2) / (w1) / (w2); a huge
# bound collapses the whole tree into ONE bucket — the monolithic exchange
# inside the identical bucketed scaffold (the flat GSPMD psum differs by
# reassociation, so monolithic-vs-bucketed comparisons hold the scaffold fixed)
TINY = {"overlap": {"mode": "bucketed", "bucket_mb": 0.01}}
ONE = {"overlap": {"mode": "bucketed", "bucket_mb": 64.0}}


def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(seed=0):
    data = random_dataset(8, HIDDEN, seed=seed)
    return np.stack([d[0] for d in data]), np.stack([d[1] for d in data])


def _train(eng, steps, seed=0):
    xs, ys = _batch(seed)
    losses = []
    for _ in range(steps):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def _external_master_pair(n):
    """Flat-shard external-master (init, apply) pair (the bench optimizer's
    structure at test scale) — triggers the engine's external-master fused
    step path."""
    def init(params):
        flat = jnp.concatenate([p.reshape(-1).astype(jnp.float32)
                                for p in jax.tree_util.tree_leaves(params)])
        shard = flat[: flat.shape[0] // n]
        return {"master": shard, "m1": jnp.zeros_like(shard),
                "m2": jnp.zeros_like(shard)}

    def apply(grads, opt_state, master, step, hyper):
        g = jnp.concatenate([x.reshape(-1).astype(jnp.float32)
                             for x in jax.tree_util.tree_leaves(grads)])
        gs = g[: opt_state["master"].shape[0]]
        m1 = 0.9 * opt_state["m1"] + 0.1 * gs
        m2 = 0.999 * opt_state["m2"] + 0.001 * gs * gs
        new_master = opt_state["master"] - hyper["lr"] * m1 / (jnp.sqrt(m2) + 1e-8)
        return None, {"master": new_master, "m1": m1, "m2": m2}

    apply.external_master = True
    return init, apply


# ----------------------------------------------------------- bucket planning
def test_bucket_partition_deterministic_and_covering():
    params = SimpleModel(HIDDEN).init(jax.random.PRNGKey(0))
    # 0.01 MB = 10485 bytes: b1+b2 (512 B) fit one bucket, each 64x64 weight
    # (16384 B) overflows into its own — partition depends on shapes only
    got = bucket_partition(params, int(0.01 * 1024 * 1024))
    leaves = jax.tree_util.tree_leaves(params)
    sizes = [int(np.prod(l.shape)) for l in leaves]
    assert sizes == [64, 64, 4096, 4096]  # b1, b2, w1, w2 (dict order)
    assert got == [[0, 1], [2], [3]]
    assert got == bucket_partition(params, int(0.01 * 1024 * 1024))  # stable
    # every leaf exactly once, in tree order
    assert sorted(sum(got, [])) == list(range(len(leaves)))
    # a bound below the largest leaf still gives it its own (oversized) bucket
    tiny = bucket_partition(params, 16)
    assert tiny == [[0], [1], [2], [3]]
    # a huge bound collapses to one bucket
    assert bucket_partition(params, 1 << 30) == [[0, 1, 2, 3]]


def test_bucket_plan_geometry():
    params = SimpleModel(HIDDEN).init(jax.random.PRNGKey(0))
    plan = bucket_plan(params, int(0.01 * 1024 * 1024), dp=8)
    # n_pad rounds each bucket up to the dp x lane quantum (8 x 128 = 1024):
    # every one of the dp scatter chunks is a whole multiple of the lane width
    assert [(b["leaf_indices"], b["n"], b["n_pad"]) for b in plan] == \
        [((0, 1), 128, 1024), ((2,), 4096, 4096), ((3,), 4096, 4096)]
    for b in plan:
        assert b["n_pad"] % (8 * 128) == 0 and sum(b["sizes"]) == b["n"]
    ragged = bucket_plan({"a": jnp.zeros((5,))}, 1 << 20, dp=8)
    assert ragged[0]["n_pad"] == 1024


def test_bucketed_error_state_shapes_layout():
    params = SimpleModel(HIDDEN).init(jax.random.PRNGKey(0))
    topo = CommTopology(8, 2)
    plan = bucket_plan(params, int(0.01 * 1024 * 1024), dp=8)
    (dp_w, we_cols), (dp_s, se_cols) = bucketed_error_state_shapes(plan, topo)
    assert dp_w == dp_s == 8
    assert we_cols == sum(b["n_pad"] // topo.slice_size for b in plan)
    assert se_cols == sum(b["n_pad"] // 8 for b in plan)
    # a single all-covering bucket reproduces the monolithic layout
    one = bucket_plan(params, 1 << 30, dp=8)
    assert bucketed_error_state_shapes(one, topo) == \
        error_state_shapes(one[0]["n_pad"], topo)


# ------------------------------------------- bit-equality across step paths
def test_bucketed_bit_equal_monolithic_hierarchical_two_jit():
    """Two-jit path, hierarchical topology: the bucketed exchange reassociates
    NOTHING per element (same reduce-scatter/psum/all-gather tree per bucket),
    so grads must be BIT-equal to the monolithic two-level exchange."""
    mono = _build(zero_optimization={"stage": 2},
                  comm={"mode": "hierarchical", "dcn_slices": 2})
    bkt = _build(zero_optimization={"stage": 2},
                 comm=dict({"mode": "hierarchical", "dcn_slices": 2}, **TINY))
    assert len(bkt._overlap_plan) == 3
    xs, ys = _batch()
    bx = mono.shard_batch((xs, ys))
    l1, g1 = mono._jit_loss_and_grad(mono.params, mono.scaler_state.cur_scale,
                                     *bx)
    l2, g2 = bkt._jit_loss_and_grad(bkt.params, bkt.scaler_state.cur_scale,
                                    *bx)
    assert float(l1) == float(l2)
    for k in g1:
        np.testing.assert_array_equal(np.asarray(g1[k]), np.asarray(g2[k]),
                                      err_msg=k)


def test_bucketed_bit_equal_single_bucket_flat_two_jit():
    """Two-jit path, flat topology: tiny buckets vs one all-covering bucket
    (the monolithic exchange in the same shard_map scaffold) are bit-equal."""
    one = _build(zero_optimization={"stage": 2},
                 comm=dict({"mode": "flat"}, **ONE))
    bkt = _build(zero_optimization={"stage": 2},
                 comm=dict({"mode": "flat"}, **TINY))
    assert len(one._overlap_plan) == 1 and len(bkt._overlap_plan) == 3
    xs, ys = _batch()
    bx = one.shard_batch((xs, ys))
    l1, g1 = one._jit_loss_and_grad(one.params, one.scaler_state.cur_scale,
                                    *bx)
    l2, g2 = bkt._jit_loss_and_grad(bkt.params, bkt.scaler_state.cur_scale,
                                    *bx)
    assert float(l1) == float(l2)
    for k in g1:
        np.testing.assert_array_equal(np.asarray(g1[k]), np.asarray(g2[k]),
                                      err_msg=k)


def test_bucketed_bit_equal_fused_standard_path():
    """Fused standard step ({"fused_step": true}): per-step losses bit-equal
    between tiny buckets and the single-bucket monolithic exchange."""
    one = _build(fused_step=True, comm=dict({"mode": "flat"}, **ONE))
    bkt = _build(fused_step=True, comm=dict({"mode": "flat"}, **TINY))
    assert one._run_fused_step is not None
    assert bkt._run_fused_step is not None
    np.testing.assert_array_equal(_train(one, 3), _train(bkt, 3))


def test_bucketed_bit_equal_fused_external_master_path():
    """Fused external-master step (gas == 1, external optimizer): per-step
    losses bit-equal between tiny buckets and the single-bucket exchange."""
    def build(comm):
        model = SimpleModel(HIDDEN)
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
            optimizer=_external_master_pair(4),
            config_params=simple_config(
                zero_optimization={"stage": 2},
                zero_allow_untested_optimizer=True, comm=comm))
        return eng

    one = build(dict({"mode": "hierarchical", "dcn_slices": 2}, **ONE))
    bkt = build(dict({"mode": "hierarchical", "dcn_slices": 2}, **TINY))
    assert one._run_fused_step is not None
    assert bkt._run_fused_step is not None
    np.testing.assert_array_equal(_train(one, 3), _train(bkt, 3))


# ------------------------------------------------ compressed overlap / EF
def test_compressed_overlap_ef_state_layout_and_training():
    """Two-jit compressed path: the engine's persistent EF buffers take the
    bucketed per-bucket layout, stay zero through the uncompressed warmup,
    accumulate once compression starts, and the run keeps training within
    the documented tolerance of the monolithic compressed exchange."""
    mono = _build(zero_optimization={"stage": 2},
                  comm={"mode": "hierarchical_compressed", "dcn_slices": 2,
                        "compress_start_step": 2})
    bkt = _build(zero_optimization={"stage": 2},
                 comm=dict({"mode": "hierarchical_compressed",
                            "dcn_slices": 2, "compress_start_step": 2},
                           **TINY))
    topo = bkt._comm_topo
    plan = bkt._overlap_plan
    assert len(plan) == 3
    (_, we_cols), (_, se_cols) = bucketed_error_state_shapes(plan, topo)
    assert bkt._comm_we.shape == (8, we_cols)
    assert bkt._comm_se.shape == (8, se_cols)
    assert not np.asarray(bkt._comm_we).any()
    l_mono = _train(mono, 12)
    l_bkt = _train(bkt, 12)
    # warmup steps run the UNCOMPRESSED bucketed exchange -> bit-equal to the
    # monolithic hierarchical warmup
    np.testing.assert_array_equal(l_bkt[:2], l_mono[:2])
    # compressed steps: per-bucket RMS scale segments reassociate, so parity
    # is the documented tolerance, and training still converges
    assert max(abs(a - b) for a, b in zip(l_bkt[2:], l_mono[2:])) < 0.1
    assert l_bkt[-1] < l_bkt[0]
    assert np.asarray(bkt._comm_we).any()  # EF residual accumulated
    assert np.asarray(bkt._comm_se).any()


# ---------------------------------------------------- off-switch invariance
def test_overlap_off_is_hlo_instruction_identical():
    """With comm.overlap absent (or mode "off") the compiled two-jit step is
    HLO-instruction-identical to the pre-overlap engine's."""
    base = _build(zero_optimization={"stage": 2},
                  comm={"mode": "hierarchical", "dcn_slices": 2})
    off = _build(zero_optimization={"stage": 2},
                 comm={"mode": "hierarchical", "dcn_slices": 2,
                       "overlap": {"mode": "off"}})
    assert base._overlap_plan is None and off._overlap_plan is None
    xs, ys = _batch()
    h1 = optimized_hlo(base._jit_loss_and_grad, base.params,
                       base.scaler_state.cur_scale, xs, ys)
    h2 = optimized_hlo(off._jit_loss_and_grad, off.params,
                       off.scaler_state.cur_scale, xs, ys)
    assert h1 == h2


def test_flat_overlap_falls_back_when_dp_is_one():
    """overlap requires a data-parallel exchange: a dp==1-equivalent setup
    (model too small / no sharded grads) must not crash — the plan is built
    only when the exchange exists (dp > 1 on the 8-device mesh, so here we
    just pin that the engine records a plan exactly when overlap is active)."""
    eng = _build(zero_optimization={"stage": 2},
                 comm=dict({"mode": "flat"}, **TINY))
    assert eng._overlap_plan is not None
    assert all(b["n_pad"] % eng.dp_size == 0 for b in eng._overlap_plan)
