"""Pipelined ZeRO-Offload step tests (CPU-only, no accelerator needed).

The pipeline claim is a WALL-CLOCK claim, so it is proven here with an
injectable transfer executor that adds simulated per-item latency: the serial
executor's step must cost ~ Σfetch + Σadam + Σpush while the pipelined
executor's step must cost <= 1.15 x max(Σfetch, Σadam, Σpush) — and both must
produce bit-identical optimizer state (Adam is elementwise, so chunking and
overlap may not change a single bit)."""

import threading
import time

import numpy as np
import pytest

import jax

from deepspeed_tpu.ops.cpu_adam import (DeepSpeedCPUAdam, PipelinedTransferExecutor,
                                        SerialTransferExecutor)
from deepspeed_tpu.runtime.zero.sharding import chunk_spans


def _params(rng, n_leaves=8, size=2000):
    return {f"p{i}": rng.normal(size=(size,)).astype(np.float32)
            for i in range(n_leaves)}


def _grads(rng, params):
    return {k: rng.normal(size=v.shape).astype(np.float32) for k, v in params.items()}


class _LatencyMixin:
    """Adds per-item sleep to each lane and records lane busy seconds plus the
    maximum number of simultaneously-running lane tasks (the caller thread's
    Adam is not counted, so max_concurrency >= 2 means the fetch and push lanes
    really ran at the same time)."""

    def _init_latency(self, fetch_delay, push_delay):
        self.fetch_delay, self.push_delay = fetch_delay, push_delay
        self._lock = threading.Lock()
        self._active = 0
        self.max_concurrency = 0
        self.lane_busy = {"fetch": 0.0, "push": 0.0}

    def _wrap(self, fn, delay, lane):
        def run(*args):
            with self._lock:
                self._active += 1
                self.max_concurrency = max(self.max_concurrency, self._active)
            t0 = time.perf_counter()
            try:
                time.sleep(delay)
                return fn(*args)
            finally:
                with self._lock:
                    self._active -= 1
                    self.lane_busy[lane] += time.perf_counter() - t0
        return run


class LatencySerialExecutor(_LatencyMixin, SerialTransferExecutor):
    def __init__(self, fetch_delay, push_delay):
        self._init_latency(fetch_delay, push_delay)

    def submit_fetch(self, fn, *args):
        return super().submit_fetch(self._wrap(fn, self.fetch_delay, "fetch"), *args)

    def submit_push(self, fn, *args):
        return super().submit_push(self._wrap(fn, self.push_delay, "push"), *args)


class LatencyPipelinedExecutor(_LatencyMixin, PipelinedTransferExecutor):
    def __init__(self, fetch_delay, push_delay):
        super().__init__()
        self._init_latency(fetch_delay, push_delay)

    def submit_fetch(self, fn, *args):
        return super().submit_fetch(self._wrap(fn, self.fetch_delay, "fetch"), *args)

    def submit_push(self, fn, *args):
        return super().submit_push(self._wrap(fn, self.push_delay, "push"), *args)


def _run_steps(opt, grads_seq, **hyper):
    for step, g in enumerate(grads_seq, start=1):
        opt.step_regions(opt.begin_grad_fetch(g), step=step, **hyper)


def test_pipelined_step_bit_equal_to_serial():
    """Overlap and chunking may not change the update by a single bit: Adam is
    elementwise, so a chunked kernel call sequence must equal the one-shot call."""
    rng = np.random.default_rng(0)
    params = _params(rng, n_leaves=6, size=3001)  # odd size: chunks don't divide evenly
    grads_seq = [_grads(rng, params) for _ in range(3)]
    hyper = dict(lr=1e-2, weight_decay=0.01, grad_scale=0.5)

    serial = DeepSpeedCPUAdam(params, pipeline=False)
    serial.transfer_executor = SerialTransferExecutor()
    piped = DeepSpeedCPUAdam(params, pipeline=True, pipeline_depth=3,
                             max_region_elements=512)  # forces ~6 chunks per leaf
    try:
        _run_steps(serial, grads_seq, **hyper)
        _run_steps(piped, grads_seq, **hyper)
        np.testing.assert_array_equal(piped.fp32, serial.fp32)
        np.testing.assert_array_equal(piped.exp_avg, serial.exp_avg)
        np.testing.assert_array_equal(piped.exp_avg_sq, serial.exp_avg_sq)
    finally:
        piped.close()


def test_pipelined_wall_clock_overlaps_simulated_latency():
    """With F=40ms fetch / P=10ms push injected per region, the serial step costs
    about the SUM of the lanes while the pipelined step costs about the MAX —
    the ISSUE's total ~ max(Σfetch, Σadam, Σpush) acceptance bound."""
    F, P, N = 0.040, 0.010, 8
    rng = np.random.default_rng(1)
    params = _params(rng, n_leaves=N, size=1500)
    g = _grads(rng, params)
    hyper = dict(lr=1e-3, weight_decay=0.0)

    serial = DeepSpeedCPUAdam(params)
    serial.transfer_executor = LatencySerialExecutor(F, P)
    t0 = time.perf_counter()
    serial.step_regions(serial.begin_grad_fetch(g), step=1, **hyper)
    serial_wall = time.perf_counter() - t0
    s_fetch = serial.transfer_executor.lane_busy["fetch"]
    s_push = serial.transfer_executor.lane_busy["push"]
    s_adam = serial.last_step_timing["host_adam"]
    # serial ~ sum of the three lanes (sleep scheduling noise only adds time,
    # so the lower bound is the meaningful one)
    assert serial_wall >= 0.85 * (s_fetch + s_adam + s_push), \
        (serial_wall, s_fetch, s_adam, s_push)

    piped = DeepSpeedCPUAdam(params, pipeline_depth=2)
    ex = piped.transfer_executor = LatencyPipelinedExecutor(F, P)
    try:
        t0 = time.perf_counter()
        piped.step_regions(piped.begin_grad_fetch(g), step=1, **hyper)
        piped_wall = time.perf_counter() - t0
    finally:
        piped.close()
        ex.shutdown()
    p_fetch = ex.lane_busy["fetch"]
    p_push = ex.lane_busy["push"]
    p_adam = piped.last_step_timing["host_adam"]
    bound = 1.15 * max(p_fetch, p_adam, p_push)
    assert piped_wall <= bound, (piped_wall, bound, p_fetch, p_adam, p_push)
    # the lanes really overlapped: >= 2 executor tasks in flight at once, and the
    # pipelined wall beat the serial wall outright
    assert ex.max_concurrency >= 2, ex.max_concurrency
    assert piped_wall < serial_wall, (piped_wall, serial_wall)
    # identical state out of both walks
    np.testing.assert_array_equal(piped.fp32, serial.fp32)


def test_pipelined_timing_schema_and_overlap_fields():
    """step_regions must publish the lane-busy/overlap schema bench.py consumes."""
    rng = np.random.default_rng(2)
    params = _params(rng, n_leaves=4, size=900)
    opt = DeepSpeedCPUAdam(params, max_region_elements=256)
    try:
        opt.step_regions(opt.begin_grad_fetch(_grads(rng, params)), step=1, lr=1e-3)
        t = opt.last_step_timing
    finally:
        opt.close()
    for key in ("fetch_wait", "host_adam", "push", "total", "fetch_busy",
                "push_busy", "pipeline_depth", "region_cap", "n_work_items",
                "regions"):
        assert key in t, key
    assert t["pipeline_depth"] == 2 and t["region_cap"] == 256
    assert t["n_work_items"] == sum(-(-r.size // 256) for r in opt._regions)
    assert len(t["regions"]) == len(opt._regions)
    for r in t["regions"]:
        assert r["chunks"] >= 1 and r["size"] > 0
        assert r["fetch"] >= 0 and r["adam"] >= 0 and r["push"] >= 0


def test_region_cap_splits_and_covers():
    """An explicit max_region_elements must cap every work item's covered range
    and the ranges must exactly tile each region."""
    cap = 1024
    rng = np.random.default_rng(3)
    params = {"big": rng.normal(size=(5000,)).astype(np.float32),
              "small": rng.normal(size=(100,)).astype(np.float32)}
    opt = DeepSpeedCPUAdam(params, max_region_elements=cap)
    try:
        handles = opt.begin_grad_fetch(_grads(rng, params))
        covered = {}
        for kind, _, r, rel_lo, rel_hi, win in handles:
            assert rel_hi - rel_lo <= cap
            assert win <= rel_lo and rel_hi <= win + cap  # window carries the range
            covered.setdefault(id(r), []).append((rel_lo, rel_hi, r.size))
        assert len(covered) == 2
        for spans in covered.values():
            spans.sort()
            assert spans[0][0] == 0 and spans[-1][1] == spans[0][2]
            for (a_lo, a_hi, _), (b_lo, b_hi, _) in zip(spans, spans[1:]):
                assert b_lo == a_hi  # contiguous, non-overlapping coverage
        big_items = [h for h in handles if h[2].size == 5000]
        assert len(big_items) == -(-5000 // cap)
    finally:
        opt.close()


def test_chunk_spans_windowing():
    """chunk_spans: fixed-width windows (one compiled slice per cap), the last
    window right-aligned so every [lo, hi) stays inside its window."""
    assert chunk_spans(10, None) == [(0, 10, 0)]
    assert chunk_spans(10, 0) == [(0, 10, 0)]
    assert chunk_spans(10, 16) == [(0, 10, 0)]
    spans = chunk_spans(10, 4)
    assert spans == [(0, 4, 0), (4, 8, 4), (8, 10, 6)]
    for lo, hi, win in spans:
        assert win <= lo and hi <= win + 4
    assert chunk_spans(8, 4) == [(0, 4, 0), (4, 8, 4)]


def test_autotune_sets_cap_once_and_respects_pin():
    rng = np.random.default_rng(4)
    params = _params(rng, n_leaves=3, size=4000)
    auto = DeepSpeedCPUAdam(params)  # max_region_elements="auto"
    try:
        assert not auto._autotuned
        auto.step_regions(auto.begin_grad_fetch(_grads(rng, params)), step=1, lr=1e-3)
        assert auto._autotuned
        assert (1 << 20) <= auto._auto_cap <= (64 << 20)
        cap_after_first = auto._auto_cap
        auto.step_regions(auto.begin_grad_fetch(_grads(rng, params)), step=2, lr=1e-3)
        assert auto._auto_cap == cap_after_first  # tunes once, not every step
    finally:
        auto.close()

    pinned = DeepSpeedCPUAdam(params, max_region_elements=512)
    try:
        pinned.step_regions(pinned.begin_grad_fetch(_grads(rng, params)), step=1,
                            lr=1e-3)
        assert not pinned._autotuned and pinned.region_cap() == 512
    finally:
        pinned.close()

    with pytest.raises(ValueError, match="max_region_elements"):
        DeepSpeedCPUAdam(params, max_region_elements=-5)


def test_serial_executor_disables_chunking():
    """pipeline=False must reproduce the legacy one-item-per-region walk."""
    rng = np.random.default_rng(5)
    params = _params(rng, n_leaves=3, size=3000)
    opt = DeepSpeedCPUAdam(params, pipeline=False, max_region_elements=512)
    assert opt.region_cap() is None  # cap only applies to the pipelined walk
    handles = opt.begin_grad_fetch(_grads(rng, params))
    assert len(handles) == len(opt._regions)
    opt.step_regions(handles, step=1, lr=1e-3)
    assert opt.last_step_timing["pipeline_depth"] == 1
