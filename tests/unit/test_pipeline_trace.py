"""Pipeline schedule observatory tests: span recording through the instruction
executor, goodput decomposition + telemetry scalars, measured-vs-analytic bubble
agreement on a 4-stage CPU mesh, straggler naming under an injected delay, the
HLO-identity guarantee when disabled, flight-recorder embedding, and the
Perfetto exporter (golden-file byte stability + CLI round trips).
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils.hlo import instruction_count, optimized_hlo
from deepspeed_tpu.utils.pipeline_trace import (measured_costs, simulate_schedule,
                                                simulated_bundle, serialize_trace,
                                                timeline_main, to_trace_events)
from test_pipe_engine import HIDDEN, make_pipe, pipe_config, data_iter

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden",
                      "pipeline_timeline_2x4.trace.json")


def _build(stages=2, micro=2, layers=4, batch=32, **cfg_over):
    module, params = make_pipe(num_layers=layers, num_stages=stages)
    cfg = pipe_config(batch=batch, micro=micro)
    cfg["pipeline"] = {"spmd": False}  # span recording is instruction-executor-mode
    cfg.update(cfg_over)
    eng, _, _, _ = deepspeed_tpu.initialize(model=module, model_parameters=params,
                                            config_params=cfg)
    return eng


def _trace_cfg(**pt_over):
    pt = {"enabled": True}
    pt.update(pt_over)
    return {"telemetry": {"pipeline_trace": pt}}


# ------------------------------------------------------------- span recording


def test_tracer_disabled_by_default():
    eng = _build()
    assert eng.pipe_trace is None
    eng.train_batch(data_iter(batch=16))  # untraced path still executes


def test_spans_cover_the_schedule():
    eng = _build(**_trace_cfg())
    it = data_iter(batch=16)
    eng.train_batch(it)
    eng.train_batch(it)
    assert len(eng.pipe_trace.steps) == 2
    rec = eng.pipe_trace.steps[-1]
    assert rec["kind"] == "train" and rec["schedule"] == "TrainSchedule"
    spans = rec["spans"]
    # every compute slot of the analytic replay appears as a measured span
    sim = simulate_schedule(rec["micro_batches"], eng.num_stages, "train")
    measured_slots = sorted({(sp[0], sp[1]) for sp in spans
                             if sp[2] in ("ForwardPass", "BackwardPass")})
    assert measured_slots == sim["busy_slots"]
    # micro-batch and buffer attribution
    for s in range(eng.num_stages):
        fwd_mbs = sorted(sp[3] for sp in spans if sp[0] == s and sp[2] == "ForwardPass")
        assert fwd_mbs == list(range(rec["micro_batches"])), f"stage {s}"
    assert all(sp[6] >= 0 and sp[5] >= 0 for sp in spans)


def test_eval_batch_records_inference_spans():
    eng = _build(**_trace_cfg())
    it = data_iter(batch=16)
    eng.eval_batch(it)
    rec = eng.pipe_trace.steps[-1]
    assert rec["kind"] == "eval" and rec["schedule"] == "InferenceSchedule"
    assert any(sp[2] == "ForwardPass" for sp in rec["spans"])
    assert not any(sp[2] == "BackwardPass" for sp in rec["spans"])


def test_capacity_bounds_the_ring():
    eng = _build(**_trace_cfg(capacity=2))
    it = data_iter(batch=16)
    for _ in range(4):
        eng.train_batch(it)
    assert len(eng.pipe_trace.steps) == 2
    assert eng.pipe_trace.steps[-1]["step"] == 3  # most recent kept


# ------------------------------------------------------- goodput + telemetry


def test_goodput_scalars_flow_through_telemetry(tmp_path):
    eng = _build(telemetry={"enabled": True, "output_path": str(tmp_path),
                            "pipeline_trace": {"enabled": True}})
    it = data_iter(batch=16)
    eng.train_batch(it)
    eng.telemetry.monitor.flush()
    scalars = open(os.path.join(str(tmp_path), "DeepSpeedTelemetry",
                                "scalars.jsonl")).read()
    for name in ("Pipeline/Goodput/bubble_fraction", "Pipeline/Goodput/fwd_seconds",
                 "Pipeline/Goodput/bwd_seconds", "Pipeline/Goodput/opt_seconds"):
        assert name in scalars, name
    g = eng.pipe_trace.last_schedule_goodput
    assert g["fwd_seconds"] > 0 and g["bwd_seconds"] > 0
    assert 0.0 <= g["bubble_fraction"] < 1.0
    assert len(g["per_stage_busy_seconds"]) == eng.num_stages
    # the one-release "goodput" alias is gone: the bare name means the
    # run-level goodput ledger (docs/goodput.md), not this decomposition
    assert not hasattr(eng.pipe_trace, "last_goodput")
    assert "goodput" not in eng.pipe_trace.steps[-1]


def _padded(fn, seconds):
    def wrapped(*args, **kwargs):
        time.sleep(seconds)
        return fn(*args, **kwargs)
    return wrapped


def test_four_stage_measured_bubble_matches_simulator():
    """Acceptance: on the 4-stage CPU-mesh pipeline, the bubble fraction
    reconstructed from recorded spans agrees with the analytic simulator run at
    the measured mean fwd/bwd costs, within 0.15 absolute (the stated
    tolerance). Stage fns carry fixed sleep pads so span durations dominate
    CPU dispatch jitter — at raw microsecond-scale spans the lockstep
    max-over-stages reconstruction is biased upward by per-span variance and
    the comparison is not deterministic."""
    eng = _build(stages=4, micro=8, batch=64, **_trace_cfg())
    it = data_iter(batch=8)
    eng.train_batch(it)  # warmup: stage-fn compiles land inside these spans
    for s in range(eng.num_stages - 1):
        eng._stage_fwd[s] = _padded(eng._stage_fwd[s], 0.01)
        eng._stage_bwd[s] = _padded(eng._stage_bwd[s], 0.02)
    eng._stage_last_bwd = _padded(eng._stage_last_bwd, 0.02)
    eng.train_batch(it)
    rec = eng.pipe_trace.steps[-1]
    measured = rec["schedule_goodput"]["bubble_fraction"]
    t_fwd, t_bwd = measured_costs(rec)
    expected = simulate_schedule(8, 4, "train", t_fwd=t_fwd, t_bwd=t_bwd)["bubble_fraction"]
    assert measured == pytest.approx(expected, abs=0.15), (measured, expected)
    # and the slot structure is EXACTLY the schedule's
    sim = simulate_schedule(8, 4, "train")
    slots = sorted({(sp[0], sp[1]) for sp in rec["spans"]
                    if sp[2] in ("ForwardPass", "BackwardPass")})
    assert slots == sim["busy_slots"]


def test_injected_delay_names_the_straggler():
    eng = _build(stages=4, micro=4, batch=32, **_trace_cfg())
    it = data_iter(batch=8)
    eng.train_batch(it)  # warmup
    slow = eng._stage_fwd[2]

    def delayed(p, x):
        time.sleep(0.02)
        return slow(p, x)

    eng._stage_fwd[2] = delayed
    try:
        eng.train_batch(it)
    finally:
        eng._stage_fwd[2] = slow
    straggler = eng.pipe_trace.divergence(threshold=3.0)
    assert straggler is not None and straggler["stage"] == 2, straggler
    assert eng.pipe_trace.last_schedule_goodput["straggler"]["stage"] == 2


# --------------------------------------------------------------- HLO identity


def test_pipeline_hlo_identical_when_disabled():
    """Tracing is host-side only: the compiled stage programs of a traced build
    match an untraced build instruction for instruction, so the disabled
    default is trivially identical to pre-subsystem builds."""
    eng_off = _build()
    eng_on = _build(**_trace_cfg())
    x = jnp.zeros((4, HIDDEN), jnp.float32)
    scale = jnp.asarray(1.0, jnp.float32)
    for s in range(eng_off.num_stages - 1):
        h_off = optimized_hlo(eng_off._stage_fwd[s], eng_off._select_params(s), x)
        h_on = optimized_hlo(eng_on._stage_fwd[s], eng_on._select_params(s), x)
        assert instruction_count(h_off) > 0
        assert instruction_count(h_off) == instruction_count(h_on), f"stage {s} fwd"
    last = eng_off.num_stages - 1
    h_off = optimized_hlo(eng_off._stage_last_bwd, eng_off._select_params(last), x, x, scale)
    h_on = optimized_hlo(eng_on._stage_last_bwd, eng_on._select_params(last), x, x, scale)
    assert instruction_count(h_off) == instruction_count(h_on), "last-stage bwd"


# ------------------------------------------------- flight recorder embedding


def test_flight_recorder_embeds_span_bundle(tmp_path):
    eng = _build(numerics={"enabled": True, "dump_dir": str(tmp_path)},
                 **_trace_cfg())
    it = data_iter(batch=16)
    eng.train_batch(it)
    rec = eng._numerics.recorder
    assert rec.pipeline_trace is eng.pipe_trace
    path = rec.trigger("manual_test")
    bundle = json.load(open(path))
    embedded = bundle["pipeline_trace"]
    assert embedded["kind"] == "pipeline_trace"
    assert embedded["stages"] == eng.num_stages
    assert len(embedded["steps"]) == 1
    # the timeline CLI resolves the flight-recorder dump directly
    out = os.path.join(str(tmp_path), "dump.trace.json")
    assert timeline_main([path, "-o", out]) == 0
    trace = json.load(open(out))
    assert any(e["ph"] == "X" for e in trace["traceEvents"])


# ------------------------------------------------------------ Perfetto export


def test_perfetto_export_matches_golden():
    """2-stage x 4-microbatch deterministic bundle serializes byte-identically
    to the committed golden file and round-trips with the required fields."""
    bundle = simulated_bundle(4, 2)
    data = serialize_trace(to_trace_events(bundle))
    assert data == serialize_trace(to_trace_events(simulated_bundle(4, 2)))  # stable
    golden = open(GOLDEN).read()
    assert data == golden
    trace = json.loads(data)
    slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert slices, "no complete events"
    for ev in slices:
        for field in ("ph", "ts", "dur", "pid", "tid", "name"):
            assert field in ev, field
        assert ev["tid"] in (0, 1)
    # one thread-name metadata track per stage + counter tracks present
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert names == {"stage 0", "stage 1"}
    counters = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
    assert "bubble_fraction" in counters
    assert any(n.endswith("buffers") for n in counters)


def test_timeline_cli_on_live_bundle(tmp_path, capsys):
    eng = _build(**_trace_cfg(dump_dir=str(tmp_path)))
    eng.train_batch(data_iter(batch=16))
    path = eng.pipe_trace.dump()
    assert timeline_main([path]) == 0
    out = capsys.readouterr().out
    assert "trace events" in out
    produced = path[:-5] + ".trace.json"
    trace = json.load(open(produced))
    assert trace["otherData"]["stages"] == 2
    assert any(e.get("cat") == "fwd" for e in trace["traceEvents"])


def test_timeline_cli_rejects_traceless_input(tmp_path, capsys):
    path = os.path.join(str(tmp_path), "not_a_bundle.json")
    json.dump({"reason": "whatever", "steps": []}, open(path, "w"))
    assert timeline_main([path]) == 2
    assert "no pipeline_trace bundle" in capsys.readouterr().out


def test_ds_tpu_timeline_subprocess(tmp_path):
    """The shipped CLI entry point converts a bundle end to end."""
    bundle_path = os.path.join(str(tmp_path), "bundle.json")
    json.dump(simulated_bundle(4, 2), open(bundle_path, "w"))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds-tpu"), "timeline", bundle_path],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "trace events" in proc.stdout
    trace = json.load(open(bundle_path[:-5] + ".trace.json"))
    assert trace["traceEvents"]
