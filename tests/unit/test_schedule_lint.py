"""Static schedule lint: every TrainSchedule/InferenceSchedule stream must obey
the send/recv rendezvous and buffer-lifetime invariants the instruction executor
relies on. A cheap regression fence for future schedule changes — the symbolic
replay in utils/pipeline_trace.py re-executes the merged streams exactly the way
runtime/pipe/engine.py does (sends before recvs within a merged step) and fails
on the first violated invariant instead of a KeyError deep inside a train run.
"""

import pytest

import deepspeed_tpu.runtime.pipe.schedule as schedule
from deepspeed_tpu.utils.pipeline_trace import (ScheduleLintError,
                                                _instruction_streams, _replay,
                                                lint_schedule, simulate_schedule)

GRID = [(m, p) for p in (1, 2, 3, 4, 6) for m in (1, 2, 3, 4, 8, 16)]


@pytest.mark.parametrize("micro_batches,stages", GRID)
def test_train_schedule_lints_clean(micro_batches, stages):
    stats = lint_schedule(micro_batches, stages, "train")
    assert stats["total_steps"] == 2 * (micro_batches + stages - 1)


@pytest.mark.parametrize("micro_batches,stages", GRID)
def test_inference_schedule_lints_clean(micro_batches, stages):
    stats = lint_schedule(micro_batches, stages, "inference")
    assert stats["total_steps"] == micro_batches + stages - 1


def test_lint_catches_dropped_send():
    """Removing one SendActivation strands its receiver: the matching recv must
    be reported against the adjacent stage."""
    streams, rings = _instruction_streams(4, 2, "train")
    for step in streams[0]:
        drop = [c for c in step if isinstance(c, schedule.SendActivation)]
        if drop:
            step.remove(drop[0])
            break
    with pytest.raises(ScheduleLintError, match="no matching SendActivation"):
        _replay(streams, rings, 4, "train")


def test_lint_catches_corrupted_buffer_id():
    """Pointing a ForwardPass at a never-loaded buffer is a use-before-load."""
    streams, rings = _instruction_streams(4, 2, "train")
    for step in streams[0]:
        for i, c in enumerate(step):
            if isinstance(c, schedule.ForwardPass):
                step[i] = schedule.ForwardPass(buffer_id=c.buffer_id + 17)
                with pytest.raises(ScheduleLintError, match="before load/recv"):
                    _replay(streams, rings, 4, "train")
                return
    pytest.fail("no ForwardPass found in stage-0 stream")


def test_lint_catches_overfull_ring():
    """Three eager sends against a two-slot receiver ring trip the in-flight
    bound at the third send, before any recv runs. Each send sits one merged
    step after its forward pass (sends execute first within a step)."""
    s0 = [[schedule.LoadMicroBatch(buffer_id=0), schedule.ForwardPass(buffer_id=0)],
          [schedule.SendActivation(buffer_id=0), schedule.LoadMicroBatch(buffer_id=1),
           schedule.ForwardPass(buffer_id=1)],
          [schedule.SendActivation(buffer_id=1), schedule.LoadMicroBatch(buffer_id=2),
           schedule.ForwardPass(buffer_id=2)],
          [schedule.SendActivation(buffer_id=2)]]
    s1 = [[], [], [], []]
    with pytest.raises(ScheduleLintError, match="in flight"):
        _replay([s0, s1], [3, 2], 3, "train")


@pytest.mark.parametrize("micro_batches,stages", [(m, p) for m, p in GRID if p > 1])
def test_simulator_matches_closed_form_bubble(micro_batches, stages):
    """At uniform compute cost the lockstep replay reproduces the
    PipeDream-flush closed form (p-1)/(m+p-1) exactly."""
    sim = simulate_schedule(micro_batches, stages, "train")
    expect = (stages - 1) / (micro_batches + stages - 1)
    assert sim["bubble_fraction"] == pytest.approx(expect, abs=1e-12)


@pytest.mark.parametrize("micro_batches,stages", GRID)
def test_simulator_occupancy_within_ring(micro_batches, stages):
    for kind in ("train", "inference"):
        sim = simulate_schedule(micro_batches, stages, kind)
        for s, (peak, ring) in enumerate(zip(sim["peak_buffer_occupancy"],
                                             sim["num_pipe_buffers"])):
            assert peak <= ring, (kind, s)


def test_simulator_single_stage_has_no_bubble():
    sim = simulate_schedule(8, 1, "train")
    assert sim["bubble_fraction"] == 0.0
    assert sim["per_stage_idle_slots"] == [0]
