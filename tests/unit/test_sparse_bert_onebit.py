"""Combined workload: long-sequence sparse-attention BERT encoder trained with 1-bit
Adam through the engine — BASELINE.json's "Long-seq sparse-attention BERT + 1-bit Adam
compressed allreduce over ICI" config, exercised end to end on the 8-device mesh
(warmup AND compressed phases)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import BertSparseSelfAttention, FixedSparsityConfig
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

VOCAB, SEQ, HID, HEADS, LAYERS = 64, 64, 32, 4, 2


class SparseBertEcho:
    """Tiny sparse-attention encoder + tied head; loss = CE reconstructing the input
    tokens (learnable fast, exercises the sparse kernels + engine end to end)."""

    def __init__(self):
        cfg = FixedSparsityConfig(num_heads=HEADS, block=16, num_local_blocks=2,
                                  num_global_blocks=1, attention="bidirectional")
        self.attn = [BertSparseSelfAttention(HID, HEADS, cfg) for _ in range(LAYERS)]

    def init(self, rng):
        ks = jax.random.split(rng, 3 * LAYERS + 1)
        params = {"embed": jax.random.normal(ks[0], (VOCAB, HID), jnp.float32) * 0.1,
                  "layers": []}
        for i in range(LAYERS):
            # every weight live from step 1: 1-bit Adam freezes the variance estimate at
            # freeze_step, so parameters whose gradients only wake up later would divide
            # a full-size compressed momentum by a near-zero frozen sqrt(v)
            params["layers"].append({
                "attn": self.attn[i].init(ks[1 + 3 * i]),
                "ln": {"scale": jnp.ones((HID,), jnp.float32),
                       "bias": jnp.zeros((HID,), jnp.float32)},
                "ffn": {"w1": jax.random.normal(ks[2 + 3 * i], (HID, 2 * HID),
                                                jnp.float32) * 0.1,
                        "b1": jnp.zeros((2 * HID,), jnp.float32),
                        "w2": jax.random.normal(ks[3 + 3 * i], (2 * HID, HID),
                                                jnp.float32) * 0.1,
                        "b2": jnp.zeros((HID,), jnp.float32)},
            })
        return params

    @staticmethod
    def _ln(x, p):
        mean = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + 1e-5) * p["scale"] + p["bias"]

    def apply(self, params, tokens):
        x = params["embed"][tokens]
        for i, lp in enumerate(params["layers"]):
            x = x + self.attn[i].apply(lp["attn"], x)
            h = jax.nn.gelu(x @ lp["ffn"]["w1"] + lp["ffn"]["b1"])
            x = self._ln(x + h @ lp["ffn"]["w2"] + lp["ffn"]["b2"], lp["ln"])
        logits = jnp.dot(x, params["embed"].T, preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0])


@pytest.mark.slow  # triple integration (~17s); tier-1 870s cap
def test_sparse_bert_with_onebit_adam_trains(eight_devices):
    model = SparseBertEcho()
    params = model.init(jax.random.PRNGKey(0))
    FREEZE = 8
    engine = DeepSpeedEngine(
        model=model, model_parameters=params,
        mesh=build_mesh(data=8, model=1, pipe=1),
        config_params={"train_batch_size": 8, "steps_per_print": 100,
                       "optimizer": {"type": "OneBitAdam",
                                     "params": {"lr": 1e-3, "freeze_step": FREEZE}}})
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(FREEZE + 6):   # warmup (exact allreduce) + 6 compressed steps
        toks = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)  # varied batches
        loss = engine(toks)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    # compressed phase must keep converging, not just the warmup
    assert losses[-1] < losses[FREEZE], f"no progress after freeze_step: {losses}"
