"""Schedule instruction-stream parity tests (reference tests/unit/test_pipe_schedule.py)."""

import pytest

import deepspeed_tpu.runtime.pipe.schedule as schedule


def _count_type(cmds, classtype):
    return len([c for c in cmds if type(c) is classtype])


def test_pipe_inference_schedule_singlestage():
    sched = schedule.InferenceSchedule(micro_batches=4, stages=1, stage_id=0)
    assert sched.num_micro_batches == 4
    full = list(iter(sched))
    for idx, cmds in enumerate(full):
        assert len(cmds) == 2
        assert type(cmds[0]) is schedule.LoadMicroBatch
        assert type(cmds[1]) is schedule.ForwardPass
        assert cmds[0].buffer_id == cmds[1].buffer_id
    assert len(full) == sched.num_micro_batches


@pytest.mark.parametrize("micro_batches", [1, 3, 8, 10])
def test_pipe_inference_schedule_firststage(micro_batches, stages=3):
    sched = schedule.InferenceSchedule(micro_batches=micro_batches, stages=stages, stage_id=0)
    full = list(iter(sched))
    for idx, cmds in enumerate(full):
        if idx == 0:
            assert len(cmds) == 2
            assert type(cmds[0]) is schedule.LoadMicroBatch
            assert type(cmds[1]) is schedule.ForwardPass
            assert cmds[0].buffer_id == cmds[1].buffer_id
            continue
        if idx == sched.num_micro_batches:
            assert len(cmds) == 1
            assert type(cmds[0]) is schedule.SendActivation
            continue
        if idx > sched.num_micro_batches:
            assert len(cmds) == 0
            continue
        assert len(cmds) == 3
        assert _count_type(cmds, schedule.LoadMicroBatch) == 1
        assert _count_type(cmds, schedule.ForwardPass) == 1
        assert _count_type(cmds, schedule.SendActivation) == 1
    assert len(full) == micro_batches + stages - 1


@pytest.mark.parametrize("micro_batches", [1, 3, 8, 10])
def test_pipe_inference_schedule_midstage(micro_batches, stages=3):
    sched = schedule.InferenceSchedule(micro_batches=micro_batches, stages=stages, stage_id=1)
    full = list(iter(sched))
    for idx, cmds in enumerate(full):
        if idx < sched.stage:
            assert len(cmds) == 0
            continue
        if idx == sched.stage + sched.num_micro_batches:
            assert len(cmds) == 1
            assert type(cmds[0]) is schedule.SendActivation
            continue
        if idx > sched.stage + sched.num_micro_batches:
            assert len(cmds) == 0
            continue
        assert _count_type(cmds, schedule.LoadMicroBatch) == 0
        assert _count_type(cmds, schedule.ForwardPass) == 1
        assert _count_type(cmds, schedule.RecvActivation) == 1
        if idx > sched.stage:
            assert _count_type(cmds, schedule.SendActivation) == 1
    assert len(full) == micro_batches + stages - 1


@pytest.mark.parametrize("micro_batches", [1, 3, 8, 10])
def test_pipe_inference_schedule_laststage(micro_batches, stages=3):
    sched = schedule.InferenceSchedule(micro_batches=micro_batches, stages=stages, stage_id=2)
    full = list(iter(sched))
    for idx, cmds in enumerate(full):
        if idx < sched.stage or idx > sched.stage + sched.num_micro_batches:
            assert len(cmds) == 0
            continue
        assert _count_type(cmds, schedule.LoadMicroBatch) == 1
        assert _count_type(cmds, schedule.ForwardPass) == 1
        assert _count_type(cmds, schedule.RecvActivation) == 1
        assert _count_type(cmds, schedule.SendActivation) == 0
    assert len(full) == micro_batches + stages - 1


def test_pipe_train_schedule_firststage():
    sched = schedule.TrainSchedule(micro_batches=8, stages=3, stage_id=0)
    for cmds in sched:
        assert all(type(instr) is not schedule.SendGrad for instr in cmds)
        assert all(type(instr) is not schedule.RecvActivation for instr in cmds)
        for instr in cmds:
            if isinstance(instr, schedule.BufferOpInstruction):
                assert 0 <= instr.buffer_id < sched.num_pipe_buffers()


def test_pipe_train_schedule_laststage():
    sched = schedule.TrainSchedule(stages=3, micro_batches=4, stage_id=2)
    for cmds in sched:
        assert all(type(instr) is not schedule.SendActivation for instr in cmds)
        assert all(type(instr) is not schedule.RecvGrad for instr in cmds)


def test_pipe_train_schedule_singlestage():
    """With one stage, TrainSchedule degenerates to fwd/bwd per micro-batch + final step."""
    sched = schedule.TrainSchedule(micro_batches=4, stages=1, stage_id=0)
    full = list(iter(sched))
    assert len(full) == 2 * (4 + 1 - 1)
    n_fwd = sum(_count_type(c, schedule.ForwardPass) for c in full)
    n_bwd = sum(_count_type(c, schedule.BackwardPass) for c in full)
    assert n_fwd == 4 and n_bwd == 4
    assert _count_type(full[-1], schedule.OptimizerStep) == 1
    assert _count_type(full[-1], schedule.ReduceGrads) == 1
    assert _count_type(full[-1], schedule.ReduceTiedGrads) == 1


def test_pipe_train_counts_balance():
    """Every stage must execute exactly micro_batches forwards and backwards, and
    sends/recvs across adjacent stages must pair up."""
    stages = 4
    mb = 6
    streams = [list(iter(schedule.TrainSchedule(micro_batches=mb, stages=stages, stage_id=s)))
               for s in range(stages)]
    for s, full in enumerate(streams):
        flat = [i for cmds in full for i in cmds]
        assert _count_type(flat, schedule.ForwardPass) == mb
        assert _count_type(flat, schedule.BackwardPass) == mb
        sends_fwd = _count_type(flat, schedule.SendActivation)
        recvs_bwd = _count_type(flat, schedule.RecvGrad)
        if s == stages - 1:
            assert sends_fwd == 0 and recvs_bwd == 0
        else:
            assert sends_fwd == mb and recvs_bwd == mb
    # pairing: stage s sends mb activations; stage s+1 receives mb activations
    for s in range(stages - 1):
        flat_next = [i for cmds in streams[s + 1] for i in cmds]
        assert _count_type(flat_next, schedule.RecvActivation) == mb
        assert _count_type(flat_next, schedule.SendGrad) == mb


def test_pipe_stagequery():
    sched = schedule.TrainSchedule(stages=3, micro_batches=2, stage_id=0)
    assert sched.is_first_stage and not sched.is_last_stage
    sched = schedule.TrainSchedule(stages=3, micro_batches=2, stage_id=1)
    assert not sched.is_first_stage and not sched.is_last_stage
    sched = schedule.TrainSchedule(stages=3, micro_batches=2, stage_id=2)
    assert not sched.is_first_stage and sched.is_last_stage


def test_instruction_repr_and_eq_are_deterministic():
    """Sorted-kwargs repr: equal instructions built with different keyword
    orders print identically, so schedule goldens and lint diffs are stable."""
    a = schedule.PipeInstruction(zeta=1, alpha=2)
    b = schedule.PipeInstruction(alpha=2, zeta=1)
    assert a == b
    assert repr(a) == repr(b) == "PipeInstruction(alpha=2, zeta=1)"
    fwd = schedule.ForwardPass(buffer_id=3)
    assert repr(fwd) == "ForwardPass(buffer_id=3)"
    assert fwd == schedule.ForwardPass(buffer_id=3)
    assert fwd != schedule.BackwardPass(buffer_id=3)  # type-sensitive equality
    assert fwd != schedule.ForwardPass(buffer_id=4)
