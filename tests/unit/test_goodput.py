"""Run-lifecycle goodput observatory tests (docs/goodput.md).

The load-bearing property is the exact-partition invariant: for ANY event
stream, the badput class seconds sum to the run wall-clock with no interval
double-counted. The ledger takes an injectable clock precisely so that
invariant can be property-tested over seeded random streams here, away from
real time. The rest covers the billing rules (hang > replay > productive,
clamped carve-outs), persistence + fleet merge, dump-alone replay pricing,
the CLI render/diff exit-code contract, and the guarantee every observatory
in this repo ships with: the compiled step program is HLO-instruction-
identical with ``telemetry.goodput`` enabled. Ground-truth attribution under
injected faults lives in ``ds-tpu crash-sim --goodput`` (golden-pinned by
scripts/lint.sh); these tests stay fast and clock-free where possible.
"""

import json
import os
import random

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils.goodput import (
    BADPUT_CLASSES, RunLedger, diff_goodput, estimate_replay_seconds,
    fleet_goodput, goodput_main, scan_ledger_dir)
from deepspeed_tpu.utils.hlo import (collective_counts, instruction_count,
                                     optimized_hlo)
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def _fake_ledger(**kw):
    """Ledger on an injected clock: advance with cell[0] += dt."""
    cell = [100.0]
    led = RunLedger(clock=lambda: cell[0], wall=lambda: 1000.0, **kw)
    return led, cell


# ------------------------------------------------------- partition invariant


def _check_partition(led, cell):
    wall = cell[0] - led.t0
    acct = led.accounted_seconds()
    assert acct == pytest.approx(wall, abs=1e-9)
    # intervals tile [0, wall] with no gap, no overlap, no zero-length span
    if led.intervals_dropped == 0 and led.intervals:
        assert led.intervals[0][0] == pytest.approx(0.0, abs=1e-9)
        assert led.intervals[-1][1] == pytest.approx(wall, abs=1e-9)
        for (a0, a1, _), (b0, b1, _) in zip(led.intervals, led.intervals[1:]):
            assert a1 > a0 and b1 > b0
            assert b0 == pytest.approx(a1, abs=1e-9)
        per_cls = {c: 0.0 for c in BADPUT_CLASSES}
        for t0, t1, cls in led.intervals:
            per_cls[cls] += t1 - t0
        for cls in BADPUT_CLASSES:
            assert per_cls[cls] == pytest.approx(
                led.class_seconds[cls], abs=1e-9), cls


@pytest.mark.parametrize("seed", range(8))
def test_partition_invariant_over_random_event_streams(seed):
    """The headline invariant, property-tested: random spans, random clamped
    and over-large carve-outs, hang/replay/eval events in random order —
    class seconds always sum to wall exactly and the interval list tiles the
    run."""
    rng = random.Random(seed)
    led, cell = _fake_ledger()
    cell[0] += rng.uniform(0.0, 3.0)
    led.close("init", {"compile": rng.uniform(0.0, 5.0)})  # may exceed span
    led.set_replay_until(rng.randint(-1, 3))
    step = 0
    for _ in range(rng.randint(1, 40)):
        ev = rng.random()
        cell[0] += rng.uniform(0.0, 1.0)    # zero-length spans must be fine
        if ev < 0.7:
            step += 1
            carve = {}
            if rng.random() < 0.5:
                carve["checkpoint_stall"] = rng.uniform(0.0, 2.0)
            if rng.random() < 0.3:
                carve["compile"] = rng.uniform(0.0, 2.0)
            if rng.random() < 0.2:
                carve["straggler_skew"] = rng.uniform(0.0, 2.0)
            led.close_step(step, carve or None, hang=rng.random() < 0.1)
        elif ev < 0.85:
            led.close("host_gap")
            cell[0] += rng.uniform(0.0, 0.5)
            led.close_eval()
        else:
            led.close("host_gap")
        _check_partition(led, cell)
    cell[0] += rng.uniform(0.0, 1.0)
    summary = led.finalize(persist=False)
    _check_partition(led, cell)
    assert summary["wall_s"] == pytest.approx(cell[0] - led.t0, abs=1e-9)
    # finalize is idempotent: a second call closes nothing new
    assert led.finalize(persist=False)["wall_s"] == summary["wall_s"]


def test_carve_clamped_to_span():
    """A carve-out larger than the span consumes the whole span and never
    goes negative — the clamp is what makes the partition unbreakable by a
    bad (or adversarial) carve estimate."""
    led, cell = _fake_ledger()
    cell[0] += 1.0
    led.close("productive_step", {"checkpoint_stall": 10.0})
    assert led.class_seconds["checkpoint_stall"] == pytest.approx(1.0)
    assert led.class_seconds["productive_step"] == 0.0
    assert led.accounted_seconds() == pytest.approx(1.0)


def test_unknown_class_rejected():
    led, cell = _fake_ledger()
    cell[0] += 1.0
    with pytest.raises(ValueError, match="unknown badput class"):
        led.close("gpu_gap")
    with pytest.raises(ValueError, match="unknown badput class"):
        led.close("init", {"nonsense": 1.0})


def test_close_step_billing_priority():
    """hang > restart_replay > productive: a stalled step produced nothing,
    so the hang rule wins even during replay."""
    led, cell = _fake_ledger()
    led.set_replay_until(2)
    for step, hang, expect in ((1, False, "restart_replay"),
                               (2, True, "hang"),
                               (3, False, "productive_step")):
        before = dict(led.class_seconds)
        cell[0] += 1.0
        led.close_step(step, hang=hang)
        assert led.class_seconds[expect] - before[expect] == pytest.approx(1.0)
    assert (led.steps, led.replay_steps, led.hang_steps) == (3, 1, 1)


def test_scalar_items_surface_eval_under_configured_tag():
    led, cell = _fake_ledger(eval_tag="validation")
    cell[0] += 2.0
    led.close("eval")
    items = dict(led.scalar_items())
    assert items["Run/Goodput/validation_seconds"] == pytest.approx(2.0)
    assert "Run/Goodput/eval_seconds" not in items
    assert items["Run/Goodput/goodput_fraction"] == 0.0
    assert items["Run/Goodput/wall_seconds"] == pytest.approx(2.0)


# ------------------------------------------------- persistence + fleet merge


def _persisted_pair(tmp_path):
    """Two-host run: host 0 all productive, host 1 half hung."""
    paths = []
    for host, hang in ((0, False), (1, True)):
        led, cell = _fake_ledger(run_id="r1", host=host,
                                 ledger_dir=str(tmp_path))
        cell[0] += 1.0
        led.close_step(1)
        cell[0] += 1.0
        led.close_step(2, hang=hang)
        led.finalize()
        paths.append(led.ledger_path())
    return paths


def test_persist_scan_fleet_roundtrip(tmp_path):
    paths = _persisted_pair(tmp_path)
    assert [os.path.basename(p) for p in paths] == [
        "goodput_r1_host0.json", "goodput_r1_host1.json"]
    runs = scan_ledger_dir(str(tmp_path))
    assert set(runs) == {"r1"} and set(runs["r1"]) == {0, 1}
    fleet = fleet_goodput(runs["r1"])
    assert fleet["kind"] == "goodput_fleet"
    assert fleet["n_hosts"] == 2 and fleet["hosts"] == [0, 1]
    # host-seconds: 4 s total, 3 s productive, 1 s hang
    assert fleet["wall_s"] == pytest.approx(4.0)
    assert fleet["class_seconds"]["hang"] == pytest.approx(1.0)
    assert fleet["goodput_fraction"] == pytest.approx(0.75)
    assert fleet["steps"] == 4 and fleet["hang_steps"] == 1
    # the single bad host stays attributable in the per-host breakdown
    assert fleet["per_host"]["0"]["goodput_fraction"] == pytest.approx(1.0)
    assert fleet["per_host"]["1"]["goodput_fraction"] == pytest.approx(0.5)


def test_persist_is_deterministic_bytes(tmp_path):
    led, cell = _fake_ledger(run_id="det", ledger_dir=str(tmp_path))
    cell[0] += 1.0
    led.close_step(1)
    led.finalize()
    first = open(led.ledger_path(), "rb").read()
    led.persist()
    assert open(led.ledger_path(), "rb").read() == first


# ------------------------------------------------- dump-alone replay pricing


def _dump_bundle(gaps, first_step=1, first_bad=None):
    mono, step, steps = 50.0, first_step, []
    steps.append({"step": step, "mono": mono})
    for g in gaps:
        mono += g
        step += 1
        steps.append({"step": step, "mono": mono})
    out = {"span": {"mono_start": 50.0, "mono_end": mono,
                    "first_step": first_step, "last_step": step,
                    "steps_spanned": step - first_step},
           "steps": steps}
    if first_bad is not None:
        out["first_bad_step"] = first_bad
    return out


def test_estimate_replay_prices_from_median_gap():
    """One warmup-inflated interval must not skew the per-step price — the
    estimator uses the median inter-record gap, not the span mean."""
    bundle = _dump_bundle([0.8, 0.4, 0.4, 0.4])   # steps 1..5, one outlier
    n, sec = estimate_replay_seconds(bundle, 3)
    assert n == 2
    assert sec == pytest.approx(0.8)              # 2 * median(0.4)
    # span-mean fallback when records carry no stamps
    bare = {"span": bundle["span"], "steps": [{"step": 1}]}
    n, sec = estimate_replay_seconds(bare, 3)
    assert n == 2 and sec == pytest.approx(2 * 2.0 / 4)


def test_estimate_replay_stops_at_first_bad_step():
    bundle = _dump_bundle([0.4, 0.4, 0.4, 0.4], first_bad=4)
    n, _ = estimate_replay_seconds(bundle, 2)
    assert n == 2                                  # steps 3..4, not ..5
    assert estimate_replay_seconds(bundle, 9)[0] == 0


def test_estimate_replay_legacy_dump_is_zero():
    assert estimate_replay_seconds({"steps": [{"step": 1}]}, 0) == (0, 0.0)
    assert estimate_replay_seconds(None, 0) == (0, 0.0)


# ------------------------------------------------------------ CLI + diff


def test_diff_names_the_regressing_class():
    led_a, cell_a = _fake_ledger(run_id="a")
    cell_a[0] += 4.0
    led_a.close_step(1)
    a = led_a.finalize(persist=False)
    led_b, cell_b = _fake_ledger(run_id="b")
    cell_b[0] += 3.0
    led_b.close_step(1, {"checkpoint_stall": 1.0})
    b = led_b.finalize(persist=False)
    diff = diff_goodput(a, b, tolerance=0.0)
    assert diff["regressed"] is True
    assert diff["regressing_class"] == "checkpoint_stall"
    assert diff["fraction_delta"] == pytest.approx(2.0 / 3.0 - 1.0)
    # tolerance wide enough -> same delta, no regression verdict
    assert diff_goodput(a, b, tolerance=0.5)["regressed"] is False
    # no-change diff: nothing regresses, no class named
    clean = diff_goodput(a, a)
    assert clean["regressed"] is False and clean["regressing_class"] is None


def test_goodput_cli_render_diff_and_exit_codes(tmp_path, capsys):
    _persisted_pair(tmp_path)                     # run r1: fraction 0.75
    good = str(tmp_path)
    led, cell = _fake_ledger(run_id="r2", host=0,
                             ledger_dir=str(tmp_path / "worse"))
    cell[0] += 1.0
    led.close_step(1)
    cell[0] += 3.0
    led.close_step(2, hang=True)                  # run r2: fraction 0.25
    led.finalize()
    worse = str(tmp_path / "worse")
    # render: directory fleet-merges; exit 0
    assert goodput_main([good]) == 0
    out = capsys.readouterr().out
    assert "hosts=2" in out and "goodput_fraction   0.7500" in out
    # single ledger file renders too, and --timeline exports its intervals
    assert goodput_main([led.ledger_path(),
                         "--timeline", str(tmp_path / "t.trace.json")]) == 0
    trace = json.load(open(tmp_path / "t.trace.json"))
    assert any(e.get("name") == "hang" for e in trace["traceEvents"])
    # diff: regression beyond tolerance exits 1 and names the class
    rc = goodput_main(["--diff", good, worse,
                       "--json", str(tmp_path / "d.json")])
    assert rc == 1
    diff = json.load(open(tmp_path / "d.json"))
    assert diff["regressed"] is True and diff["regressing_class"] == "hang"
    assert "REGRESSED" in capsys.readouterr().out
    # same diff inside tolerance exits 0
    assert goodput_main(["--diff", good, worse, "--tolerance", "0.9"]) == 0
    # bad operands exit 2 (missing ledger, fleet --timeline)
    assert goodput_main([str(tmp_path / "empty")]) == 2
    assert goodput_main([good, "--timeline",
                         str(tmp_path / "t2.trace.json")]) == 2


def test_goodput_cli_multi_run_dir_needs_run_key(tmp_path, capsys):
    _persisted_pair(tmp_path)
    led, cell = _fake_ledger(run_id="r9", host=0, ledger_dir=str(tmp_path))
    cell[0] += 1.0
    led.close_step(1)
    led.finalize()
    assert goodput_main([str(tmp_path)]) == 2     # ambiguous without --run
    assert "--run" in capsys.readouterr().out
    assert goodput_main([str(tmp_path), "--run", "r9"]) == 0
    assert "host=0" in capsys.readouterr().out


# ------------------------------------------------------------ engine wiring


def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))


def test_engine_ledger_partitions_real_steps(tmp_path):
    """End-to-end on a live engine: the ledger opens at construction, bills
    init + compile before the first step, closes every train step, persists
    beside the configured ledger_dir, and the Run/Goodput/* scalars ride
    end_step into the telemetry stream."""
    eng = _build(telemetry={
        "enabled": True, "output_path": str(tmp_path), "job_name": "gp",
        "goodput": {"enabled": True, "ledger_dir": str(tmp_path / "led")}})
    assert eng._goodput is not None
    xs, ys = _batch()
    for _ in range(3):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()
    led = eng._goodput
    assert led.steps == 3
    assert led.class_seconds["init"] > 0.0
    assert led.class_seconds["productive_step"] > 0.0
    assert abs(led.accounted_seconds() - led.wall_seconds()) < 0.05
    summary = led.finalize()
    assert summary["goodput_fraction"] > 0.0
    data = json.load(open(led.ledger_path()))
    assert data["kind"] == "goodput" and data["steps"] == 3
    eng.telemetry.close()
    scal = open(os.path.join(str(tmp_path), "gp", "scalars.jsonl")).read()
    assert "Run/Goodput/goodput_fraction" in scal
    assert "Run/Goodput/init_seconds" in scal


def test_goodput_enabled_is_hlo_identical(tmp_path):
    """The observatory guarantee: enabling telemetry.goodput changes NOTHING
    in the compiled step program — the ledger is host-side arithmetic over
    timestamps other layers already took."""
    eng_off = _build(telemetry={"enabled": True,
                                "output_path": str(tmp_path / "off")})
    eng_on = _build(telemetry={
        "enabled": True, "output_path": str(tmp_path / "on"),
        "goodput": {"enabled": True, "ledger_dir": str(tmp_path / "led")}})
    xs, ys = _batch()
    hlos = []
    for eng in (eng_off, eng_on):
        hlos.append(optimized_hlo(eng._jit_loss_and_grad, eng.params,
                                  eng.scaler_state.cur_scale, xs, ys))
    assert instruction_count(hlos[0]) > 0
    assert instruction_count(hlos[0]) == instruction_count(hlos[1])
    assert collective_counts(hlos[0]) == collective_counts(hlos[1])
