"""Launcher tests (mirrors reference tests/unit/test_run.py:6-91 plus the per-node
rank-mapping/env logic that the reference left untested)."""

import base64
import json

import pytest

from deepspeed_tpu.launcher import runner as dsrun
from deepspeed_tpu.launcher.launch import build_rank_mapping, child_env


def test_parser_mutual_exclusion():
    """cannot specify both include and exclude (reference test_run.py:6)."""
    with pytest.raises(ValueError):
        dsrun.parse_resource_filter({}, include_str="1", exclude_str="1")


def test_num_plus_filter_rejected():
    with pytest.raises(ValueError):
        dsrun.main(args="--num_nodes 1 --include worker-0 foo.py".split())
    with pytest.raises(ValueError):
        dsrun.main(args="--num_gpus 1 --exclude worker-0:0 foo.py".split())


def test_hostfile_parse(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("worker-0 slots=4\nworker-1 slots=4\n\n# comment\n")
    pool = dsrun.fetch_hostfile(str(hostfile))
    assert list(pool.items()) == [("worker-0", 4), ("worker-1", 4)]


def test_hostfile_duplicate_rejected(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("worker-0 slots=4\nworker-0 slots=2\n")
    with pytest.raises(ValueError):
        dsrun.fetch_hostfile(str(hostfile))


def test_hostfile_bad_format(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("worker-0 4\n")
    with pytest.raises(ValueError):
        dsrun.fetch_hostfile(str(hostfile))


def test_hostfile_missing():
    assert dsrun.fetch_hostfile("/definitely/not/a/hostfile") is None


@pytest.fixture
def two_workers():
    return {"worker-0": [0, 1, 2, 3], "worker-1": [0, 1, 2, 3]}


def test_include_whole_host(two_workers):
    out = dsrun.parse_resource_filter(two_workers, include_str="worker-1")
    assert out == {"worker-1": [0, 1, 2, 3]}


def test_include_slots(two_workers):
    out = dsrun.parse_resource_filter(two_workers, include_str="worker-0@worker-1:0,2")
    assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [0, 2]}


def test_exclude_slots(two_workers):
    out = dsrun.parse_resource_filter(two_workers, exclude_str="worker-1:0")
    assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [1, 2, 3]}


def test_exclude_whole_host(two_workers):
    out = dsrun.parse_resource_filter(two_workers, exclude_str="worker-1")
    assert out == {"worker-0": [0, 1, 2, 3]}


def test_exclude_all_slots_drops_host(two_workers):
    out = dsrun.parse_resource_filter(two_workers, exclude_str="worker-0:0,1,2,3")
    assert out == {"worker-1": [0, 1, 2, 3]}


def test_filter_unknown_host(two_workers):
    with pytest.raises(ValueError):
        dsrun.parse_resource_filter(two_workers, include_str="worker-7")
    with pytest.raises(ValueError):
        dsrun.parse_resource_filter(two_workers, exclude_str="worker-0:9")


def test_filter_preserves_order(two_workers):
    out = dsrun.parse_resource_filter(two_workers, include_str="worker-1@worker-0:1")
    assert list(out.keys()) == ["worker-0", "worker-1"]


def test_world_info_roundtrip(two_workers):
    encoded = dsrun.encode_world_info(two_workers)
    assert dsrun.decode_world_info(encoded) == two_workers
    # urlsafe alphabet only (no +, /, spaces) — must survive as one shell token
    assert set(encoded) <= set("ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_=")
    json.loads(base64.urlsafe_b64decode(encoded))


def test_rank_mapping():
    world = {"worker-0": [0, 1], "worker-1": [0, 1], "worker-2": [0]}
    mapping, world_size = build_rank_mapping(world)
    assert world_size == 5
    assert mapping == {"worker-0": [0, 1], "worker-1": [2, 3], "worker-2": [4]}


def test_child_env_multi_proc_per_host():
    world = {"worker-0": [0, 1], "worker-1": [0, 1]}
    env = child_env({}, world, node_rank=1, local_rank=1, master_addr="10.0.0.1", master_port=29500)
    assert env["RANK"] == "3" and env["WORLD_SIZE"] == "4" and env["LOCAL_RANK"] == "1"
    assert env["DS_COORDINATOR_ADDRESS"] == "10.0.0.1:29500"
    assert env["DS_PROCESS_ID"] == "3" and env["DS_NUM_PROCESSES"] == "4"
    assert env["TPU_VISIBLE_DEVICES"] == "1"
    # libtpu topology: distinct per-process port, full address list, task id
    env0 = child_env({}, world, node_rank=1, local_rank=0, master_addr="10.0.0.1", master_port=29500)
    assert env["TPU_PROCESS_PORT"] != env0["TPU_PROCESS_PORT"]
    assert env["TPU_PROCESS_ADDRESSES"] == "worker-0:8476,worker-0:8477,worker-1:8476,worker-1:8477"
    assert env["CLOUD_TPU_TASK_ID"] == "3"
    assert env["TPU_PROCESS_BOUNDS"] == "1,1,4"


def test_num_gpus_exceeding_slots_rejected(tmp_path, monkeypatch):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("worker-0 slots=2\n")
    monkeypatch.setattr(dsrun.subprocess, "check_output", lambda *a, **k: b"10.0.0.1 ")
    with pytest.raises(ValueError, match="exceeds"):
        dsrun.main(args=["--hostfile", str(hostfile), "--num_gpus", "4", "train.py"])


def test_mpi_env_identity_variants(monkeypatch):
    from deepspeed_tpu.runtime import dist as ds_dist
    for k in ["DS_COORDINATOR_ADDRESS", "DS_NUM_PROCESSES", "DS_PROCESS_ID", "MASTER_ADDR",
              "WORLD_SIZE", "RANK", "OMPI_COMM_WORLD_SIZE", "MV2_COMM_WORLD_SIZE", "PMI_SIZE"]:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("DS_COORDINATOR_ADDRESS", "h0:29500")
    monkeypatch.setenv("MV2_COMM_WORLD_SIZE", "4")
    monkeypatch.setenv("MV2_COMM_WORLD_RANK", "1")
    assert ds_dist._env_identity() == ("h0:29500", 4, 1)
    monkeypatch.delenv("MV2_COMM_WORLD_SIZE")
    monkeypatch.setenv("PMI_SIZE", "2")
    monkeypatch.setenv("PMI_RANK", "0")
    assert ds_dist._env_identity() == ("h0:29500", 2, 0)


def test_child_env_one_proc_per_host():
    """slots=1 per host: the process owns every local chip — no pinning env."""
    world = {"worker-0": [0], "worker-1": [0]}
    env = child_env({"HOME": "/root"}, world, node_rank=0, local_rank=0,
                    master_addr="10.0.0.1", master_port=1234)
    assert env["RANK"] == "0" and env["WORLD_SIZE"] == "2"
    assert "TPU_VISIBLE_DEVICES" not in env
    assert env["HOME"] == "/root"


def test_env_identity_parsing(monkeypatch):
    from deepspeed_tpu.runtime import dist as ds_dist
    for k in ["DS_COORDINATOR_ADDRESS", "DS_NUM_PROCESSES", "DS_PROCESS_ID",
              "MASTER_ADDR", "MASTER_PORT", "WORLD_SIZE", "RANK", "OMPI_COMM_WORLD_SIZE"]:
        monkeypatch.delenv(k, raising=False)
    assert ds_dist._env_identity() is None
    monkeypatch.setenv("MASTER_ADDR", "host0")
    monkeypatch.setenv("MASTER_PORT", "1111")
    monkeypatch.setenv("WORLD_SIZE", "8")
    monkeypatch.setenv("RANK", "5")
    assert ds_dist._env_identity() == ("host0:1111", 8, 5)
    monkeypatch.setenv("DS_COORDINATOR_ADDRESS", "host9:2222")
    monkeypatch.setenv("DS_NUM_PROCESSES", "4")
    monkeypatch.setenv("DS_PROCESS_ID", "2")
    assert ds_dist._env_identity() == ("host9:2222", 4, 2)


def test_init_distributed_noop_single_process(monkeypatch):
    from deepspeed_tpu.runtime import dist as ds_dist
    for k in ["DS_COORDINATOR_ADDRESS", "DS_NUM_PROCESSES", "DS_PROCESS_ID",
              "MASTER_ADDR", "WORLD_SIZE", "RANK", "OMPI_COMM_WORLD_SIZE"]:
        monkeypatch.delenv(k, raising=False)
    assert ds_dist.init_distributed() is False


def test_single_node_cmd(tmp_path, monkeypatch):
    """single-host path builds a launch.py exec line (reference runner.py:309-319)."""
    captured = {}

    class FakeProc:
        returncode = 0
        def wait(self):
            return 0

    def fake_popen(cmd, env=None):
        captured["cmd"] = cmd
        return FakeProc()

    monkeypatch.setattr(dsrun.subprocess, "Popen", fake_popen)
    monkeypatch.setenv("DS_NUM_CHIPS", "4")
    with pytest.raises(SystemExit):
        dsrun.main(args=["--hostfile", "/nope", "train.py", "--foo", "1"])
    cmd = captured["cmd"]
    assert "deepspeed_tpu.launcher.launch" in cmd
    assert cmd[-3:] == ["train.py", "--foo", "1"]
    world_arg = [c for c in cmd if c.startswith("--world_info=")][0]
    world = dsrun.decode_world_info(world_arg.split("=", 1)[1])
    assert world == {"localhost": [0, 1, 2, 3]}


def test_pdsh_cmd_construction(tmp_path):
    args = dsrun.parse_args(["--hostfile", "/nope", "--master_addr", "10.0.0.1",
                             "train.py", "--epochs", "2"])
    from deepspeed_tpu.launcher.multinode_runner import PDSHRunner
    r = PDSHRunner(args, world_info_base64="V0lORk8=")
    r.add_export("XLA_FLAGS", "--xla_foo")
    cmd = r.get_cmd({}, {"worker-0": [0], "worker-1": [0]})
    joined = " ".join(cmd)
    assert cmd[0] == "pdsh"
    assert "-w worker-0,worker-1" in joined
    assert "export XLA_FLAGS=--xla_foo;" in joined
    assert "--node_rank=%n" in joined
    assert "deepspeed_tpu.launcher.launch" in joined
    assert "'2'" in joined  # non-flag user args quoted


def test_num_gpus_without_hostfile_honored(monkeypatch):
    """localhost slot count is a heuristic → --num_gpus overrides it."""
    captured = {}

    class FakeProc:
        returncode = 0
        def wait(self):
            return 0

    monkeypatch.setattr(dsrun.subprocess, "Popen",
                        lambda cmd, env=None: captured.update(cmd=cmd) or FakeProc())
    monkeypatch.delenv("DS_NUM_CHIPS", raising=False)
    with pytest.raises(SystemExit):
        dsrun.main(args=["--hostfile", "/nope", "--num_gpus", "4", "train.py"])
    world_arg = [c for c in captured["cmd"] if c.startswith("--world_info=")][0]
    assert dsrun.decode_world_info(world_arg.split("=", 1)[1]) == {"localhost": [0, 1, 2, 3]}


# ---------------------------------------------------------------------------
# Real multi-process integration: spawn 2 jax.distributed CPU processes through
# launcher/launch.py and assert loss parity with a single-process run over the
# same 2-device mesh (reference strategy: tests/unit/common.py:14-100).
# ---------------------------------------------------------------------------

import os
import subprocess
import sys

import numpy as np


# single source of truth for spawn-env scrubbing + port pick (shared with the
# dry-run rehearsal)
from launcher_worker import clean_spawn_env as _clean_env, free_port as _free_port  # noqa: E402


@pytest.mark.slow
def test_two_process_launcher_loss_parity(tmp_path):
    worker = os.path.join(os.path.dirname(__file__), "launcher_worker.py")

    # 2 real processes (1 CPU device each) through the per-node launcher
    out_multi = tmp_path / "multi.json"
    world_info = base64.urlsafe_b64encode(
        json.dumps({"localhost": [0, 1]}).encode()).decode()
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
           "--node_rank=0", "--master_addr=127.0.0.1",
           f"--master_port={_free_port()}", f"--world_info={world_info}",
           worker, f"--out={out_multi}", "--steps=3"]
    proc = subprocess.run(cmd, env=_clean_env(PYTHONPATH=repo_root),
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, f"launcher failed:\n{proc.stdout}\n{proc.stderr}"
    multi = json.loads(out_multi.read_text())
    assert multi["world"] == 2 and multi["devices"] == 2, multi

    # single process over a forced 2-device mesh: same global math
    out_single = tmp_path / "single.json"
    proc = subprocess.run(
        [sys.executable, worker, f"--out={out_single}", "--steps=3"],
        env=_clean_env(XLA_FLAGS="--xla_force_host_platform_device_count=2"),
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, f"single-process run failed:\n{proc.stderr}"
    single = json.loads(out_single.read_text())
    assert single["world"] == 1 and single["devices"] == 2, single

    np.testing.assert_allclose(multi["losses"], single["losses"], rtol=1e-5, atol=1e-6)


def test_mpi_identity_without_coordinator(tmp_path):
    """MPI env without DS_COORDINATOR_ADDRESS negotiates the address over mpi4py
    (reference engine.py:198-235) or fails with an actionable error when mpi4py
    is absent — never silently proceeds with a wrong identity. Probed in a
    subprocess: initializing a real MPI (when present) inside the shared pytest
    process could abort or wedge the whole session."""
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    probe = (
        "import sys\n"
        "from deepspeed_tpu.runtime import dist as ds_dist\n"
        "try:\n"
        "    coord, nprocs, pid = ds_dist._env_identity()\n"
        "    assert nprocs == 2 and pid == 0 and ':' in coord, (coord, nprocs, pid)\n"
        "    print('NEGOTIATED')\n"
        "except RuntimeError as e:\n"
        "    assert 'mpi4py' in str(e), e\n"
        "    print('ACTIONABLE-ERROR')\n"
    )
    env = _clean_env(PYTHONPATH=repo_root, OMPI_COMM_WORLD_SIZE="2",
                     OMPI_COMM_WORLD_RANK="0")
    r = subprocess.run([sys.executable, "-c", probe], env=env, capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() in ("NEGOTIATED", "ACTIONABLE-ERROR"), r.stdout

    # single-rank mpirun must NOT raise: there is no world to join
    probe1 = (
        "from deepspeed_tpu.runtime import dist as ds_dist\n"
        "assert ds_dist.init_distributed() is False\n"
        "print('SINGLE-OK')\n"
    )
    env = _clean_env(PYTHONPATH=repo_root, OMPI_COMM_WORLD_SIZE="1",
                     OMPI_COMM_WORLD_RANK="0")
    r = subprocess.run([sys.executable, "-c", probe1], env=env, capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "SINGLE-OK" in r.stdout


@pytest.mark.slow
def test_two_process_offload_elastic_world_change(tmp_path):
    """The sharded-state LIFECYCLE across a world-size change (VERDICT r4 #6):
    2 real jax.distributed processes train ZeRO-2+offload and save per-process
    region files; a FRESH single-process engine (2 virtual devices — same global
    math) elastically reloads the 2-process checkpoint (merge + re-scatter) and
    continues training; the continued losses must equal an uninterrupted
    single-process run, step for step. Mirrors the reference's
    elastic-dp-change reload (stage2.py:1713-1779, engine.py:1365-1374)."""
    from launcher_worker import run_elastic_rehearsal
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    run_elastic_rehearsal(str(tmp_path), repo_root)


@pytest.mark.slow
def test_two_process_hierarchical_comm_loss_parity(tmp_path):
    """Two-level ICI+DCN comm across REAL process boundaries: 2 launcher-spawned
    jax.distributed processes x 2 virtual devices (dp 4, auto-factorized 2x2 —
    the DCN boundary IS the process boundary) train ZeRO-2 hierarchical and
    OneBitAdam hierarchical_compressed; losses must match single-process flat
    oracles over the same 4-device global math (exact-mean tolerance for
    hierarchical and the OneBit warmup, documented 1-bit tolerance after the
    freeze step). Shares the implementation with __graft_entry__'s dry run."""
    from launcher_worker import run_hierarchical_rehearsal
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    run_hierarchical_rehearsal(str(tmp_path), repo_root)


@pytest.mark.slow
def test_two_process_cluster_observatory(tmp_path):
    """Cluster observatory across REAL process boundaries (docs/cluster.md):
    2 launcher-spawned jax.distributed processes with ``telemetry.cluster``
    enabled. An injected 150 ms/step sleep on rank 1 must be NAMED as the
    straggler by rank 0's heartbeat aggregation (exercises the host-local
    dispatch column — the end-to-end wall is collective-equalised and can't
    attribute), and an injected 2 s stall against a 0.5 s hang deadline must
    produce flight-recorder dumps on BOTH hosts that ``cluster-dump``
    assembles into one report naming a stalled host and its scope. Shares
    the implementation with __graft_entry__'s dry run."""
    from launcher_worker import run_cluster_observatory_rehearsal
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    run_cluster_observatory_rehearsal(str(tmp_path), repo_root)


@pytest.mark.slow
def test_two_process_offload_region_checkpoint(tmp_path):
    """Multi-host ZeRO-Offload end-to-end: 2 real jax.distributed processes train with
    partitioned host-tier Adam, each writes ITS OWN region file on save, and a fresh
    2-process engine reloads bit-identical local buffers (the multi-host analog of the
    reference's per-rank zero_pp checkpoint files)."""
    worker = os.path.join(os.path.dirname(__file__), "launcher_worker.py")
    out = tmp_path / "offload.json"
    ckpt = tmp_path / "ckpt"
    world_info = base64.urlsafe_b64encode(
        json.dumps({"localhost": [0, 1]}).encode()).decode()
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
           "--node_rank=0", "--master_addr=127.0.0.1",
           f"--master_port={_free_port()}", f"--world_info={world_info}",
           worker, f"--out={out}", "--steps=3", "--offload", f"--ckpt_dir={ckpt}"]
    proc = subprocess.run(cmd, env=_clean_env(PYTHONPATH=repo_root),
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, f"launcher failed:\n{proc.stdout}\n{proc.stderr}"
    result = json.loads(out.read_text())
    assert result["world"] == 2 and result["roundtrip_ok"], result
    # both processes wrote region files + manifests
    files = {p.name for p in (ckpt / "t0").iterdir()}
    assert "zero_offload_proc_0_optim_states.npz" in files, files
    assert "zero_offload_proc_1_optim_states.npz" in files, files
    assert "offload_manifest_0.json" in files and "offload_manifest_1.json" in files
