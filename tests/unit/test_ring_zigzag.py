"""Zigzag causal ring attention (PR 2 tentpole).

The zigzag schedule re-shards the sequence so rank i of an n-ring holds global
chunks (i, 2n-1-i): every (rank, rotation) pair contains useful work and the
~2x masked-compute tax of the contiguous causal ring disappears. These tests pin
the contract from the ISSUE's acceptance criteria:

- exact parity (existing ring tolerances) with the dense single-chip oracle AND
  with the masked-schedule ring, forward and gradients, with and without dropout;
- identical ``collective-permute`` count and bytes per step vs the masked ring
  (HLO probe over the shard_map'ped LOCAL ring — the sharded wrapper's layout
  gathers are kept out of the program on purpose);
- the per-rotation work-balance accounting (``ring_work_schedule``) that PERF.md
  reports: zigzag computes 3 + 2(n-1) C x C blocks per rank vs the masked ring's
  3 + 4(n-1), every rotation balanced across ranks;
- the kernel-level segmented operand (global-coordinate causal mask + dropout)
  against a hand-built dense reference.
"""

import functools
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.pallas.flash_attention import (DEFAULT_MASK_VALUE,
                                                      dense_attention,
                                                      dropout_keep_reference,
                                                      flash_attention_with_lse)
from deepspeed_tpu.parallel.mesh import build_mesh, shard_map
from deepspeed_tpu.parallel.ring_attention import (ring_attention,
                                                   ring_attention_sharded,
                                                   ring_work_schedule,
                                                   zigzag_shard, zigzag_unshard)
from deepspeed_tpu.utils.hlo import (collective_bytes, collective_counts,
                                     optimized_hlo)

# B/H are broadcast dims for every parity check here — keep them minimal so the
# 8-rank interpret-mode ring compiles stay affordable inside the tier-1 budget
B, H, T, D = 1, 2, 256, 32
N_RING = 8


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(data=N_RING, model=1, pipe=1)


def qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32) for k in ks)


# ------------------------------------------------------------------ layout helpers
def test_zigzag_shard_roundtrip():
    x = jnp.arange(2 * 3 * 32 * 4, dtype=jnp.float32).reshape(2, 3, 32, 4)
    for n in (1, 2, 4, 8):
        y = zigzag_shard(x, n, axis=2)
        np.testing.assert_array_equal(np.asarray(zigzag_unshard(y, n, axis=2)),
                                      np.asarray(x))


def test_zigzag_shard_layout():
    """Rank i's slice of the sharded layout is [chunk i, chunk 2n-1-i]."""
    n = 4
    Tl = 32
    c = Tl // (2 * n)
    x = jnp.arange(Tl)[None, None, :, None]
    y = np.asarray(zigzag_shard(x, n, axis=2))[0, 0, :, 0]
    for i in range(n):
        local = y[i * 2 * c:(i + 1) * 2 * c]
        np.testing.assert_array_equal(local[:c], np.arange(i * c, (i + 1) * c))
        j = 2 * n - 1 - i
        np.testing.assert_array_equal(local[c:], np.arange(j * c, (j + 1) * c))


def test_work_schedule_accounting():
    """The analytic per-rotation table: zigzag does 2 balanced units per rotation
    (3 at the diagonal), masked does 4 with rank-dependent usefulness; both cover
    the same useful work; n=8 compute ratio is 31/17 ~ 1.82."""
    for n in (2, 4, 8):
        zz = ring_work_schedule(n, "zigzag")
        mk = ring_work_schedule(n, "masked")
        assert zz["total_computed"] == 3 + 2 * (n - 1)
        assert mk["total_computed"] == 3 + 4 * (n - 1)
        assert zz["total_useful"] == mk["total_useful"]
        # zigzag is balanced: min == max useful on every rotation; no wasted
        # compute anywhere (computed == useful except the half-masked diagonal)
        for row in zz["rotations"]:
            assert row["useful_min"] == row["useful_max"]
            if row["r"] > 0:
                assert row["computed_per_rank"] == row["useful_min"]
        # the masked ring wastes whole visits (useful_min == 0 past the diagonal)
        assert any(row["useful_min"] == 0.0 for row in mk["rotations"][1:])
    r8 = ring_work_schedule(8, "masked")["total_computed"] / \
        ring_work_schedule(8, "zigzag")["total_computed"]
    assert r8 > 1.8


# ------------------------------------------------------------------ kernel: segments
def _dense_segmented(q, k, v, q_pos, k_pos, keep=None):
    """Dense oracle for a segmented call: causal in GLOBAL coordinates."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / math.sqrt(q.shape[-1])
    mask = q_pos[:, None] >= k_pos[None, :]
    scores = jnp.where(mask, scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    if keep is not None:
        probs = probs * keep
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def test_segmented_causal_kernel_matches_dense():
    """flash_attention_with_lse(q_segments=k_segments=(off0, off1)) applies the
    causal mask in global coordinates: the interleaved [chunk lo, chunk hi]
    layout must equal a dense reference over the same global positions."""
    C, G = 64, 512  # half-chunk and pretend-global lengths
    off0, off1 = 2 * C, 6 * C  # zigzag-style: rank 2 of n=4
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (B, H, 2 * C, D), jnp.float32) for kk in ks)
    pos = jnp.concatenate([off0 + jnp.arange(C), off1 + jnp.arange(C)])

    out, _ = flash_attention_with_lse(q, k, v, causal=True, interpret=True,
                                      q_segments=(off0, off1),
                                      k_segments=(off0, off1))
    ref = _dense_segmented(q, k, v, pos, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # gradients through the segmented mask
    g = jax.random.normal(jax.random.PRNGKey(3), (B, H, 2 * C, D), jnp.float32)
    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention_with_lse(
        q, k, v, causal=True, interpret=True, q_segments=(off0, off1),
        k_segments=(off0, off1))[0] * g), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(_dense_segmented(q, k, v, pos, pos) * g),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=5e-5, err_msg=f"d{name}")


def test_segmented_dropout_hashes_global_coordinates():
    """Segmented dropout must sample exactly the whole-sequence oracle's bits at
    the interleaved global coordinates (the zigzag ring's exactness guarantee)."""
    C = 64
    off0, off1 = C, 5 * C
    rate, seed = 0.25, 77
    ks = jax.random.split(jax.random.PRNGKey(13), 3)
    q, k, v = (jax.random.normal(kk, (B, H, 2 * C, D), jnp.float32) for kk in ks)
    pos = np.concatenate([off0 + np.arange(C), off1 + np.arange(C)])
    keep_full = dropout_keep_reference(seed, B, H, 8 * C, 8 * C, rate)
    keep = jnp.asarray(np.asarray(keep_full)[:, :, pos][:, :, :, pos])

    out, _ = flash_attention_with_lse(q, k, v, causal=True, interpret=True,
                                      dropout_rate=rate, dropout_seed=seed,
                                      q_segments=(off0, off1),
                                      k_segments=(off0, off1))
    ref = _dense_segmented(q, k, v, jnp.asarray(pos), jnp.asarray(pos), keep=keep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


# ------------------------------------------------------------------ ring parity
# The 8-rank interpret-mode parity tests below are compile-bound (18-31s each):
# all but the grads-parity representative are marked `slow` so tier-1 finishes
# under the ROADMAP 870s cap; the slow set runs via `-m slow` standalone.
@pytest.mark.slow
def test_zigzag_matches_dense_and_masked(mesh):
    """schedule='zigzag' (the default causal path) vs the dense oracle AND the
    schedule='masked' ring, at the existing ring tolerances."""
    q, k, v = qkv(21)
    out_zz = ring_attention_sharded(q, k, v, mesh, causal=True, interpret=True,
                                    schedule="zigzag")
    out_mk = ring_attention_sharded(q, k, v, mesh, causal=True, interpret=True,
                                    schedule="masked")
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_zz), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_zz), np.asarray(out_mk), rtol=2e-5,
                               atol=2e-5)
    assert not out_zz.sharding.is_fully_replicated


def test_zigzag_grads_match_dense(mesh):
    q, k, v = qkv(22)
    g = jax.device_put(jax.random.normal(jax.random.PRNGKey(7), (B, H, T, D),
                                         jnp.float32),
                       NamedSharding(mesh, P(None, None, "data", None)))

    def loss_zz(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True,
                                              interpret=True,
                                              schedule="zigzag") * g)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) * g)

    gz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gz, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=5e-5, err_msg=f"d{name}")


@pytest.mark.slow
def test_zigzag_dropout_matches_global_oracle(mesh):
    """Attention dropout under the zigzag ring: the interleaved layout hashes
    global coordinates through the segment operand, so the 8-shard zigzag must
    equal dense attention with the whole-sequence oracle mask — fwd and grads."""
    rate, seed = 0.2, 4321
    q, k, v = qkv(23)
    keep = dropout_keep_reference(seed, B, H, T, T, rate)

    def loss_zz(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True,
                                              interpret=True, dropout_rate=rate,
                                              dropout_seed=seed,
                                              schedule="zigzag") ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True,
                                       dropout_keep=keep) ** 2)

    np.testing.assert_allclose(float(jax.jit(loss_zz)(q, k, v)),
                               float(loss_dense(q, k, v)), rtol=2e-5)
    gz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gz, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=5e-5, err_msg=f"d{name}")


# ------------------------------------------------------------------ collectives
def _local_ring_fn(mesh, schedule):
    spec = P(None, None, "data", None)
    return shard_map(
        functools.partial(ring_attention, axis_name="data", causal=True,
                          interpret=True, schedule=schedule),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)


@pytest.mark.slow
def test_zigzag_ppermute_count_and_bytes_match_masked(mesh):
    """Acceptance criterion: identical ppermute count AND bytes per step. Both
    schedules rotate the same [B, H, T/n, D] k/v blocks around the same ring —
    the zigzag only changes which half-blocks the flash calls compute. Lower the
    shard_map'ped LOCAL ring (layout conversion excluded — it is a one-off static
    gather outside the step) and compare compiled collectives, fwd and bwd."""
    q = jnp.zeros((1, 1, 128, 16), jnp.float32)
    stats = {}
    for schedule in ("masked", "zigzag"):
        fn = _local_ring_fn(mesh, schedule)
        txt_f = optimized_hlo(jax.jit(fn), q, q, q)
        grad_fn = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(_local_ring_fn(mesh, schedule)(q, k, v) ** 2),
            argnums=(0, 1, 2)))
        txt_b = optimized_hlo(grad_fn, q, q, q)
        stats[schedule] = {
            "fwd_count": collective_counts(txt_f).get("collective-permute", 0),
            "fwd_bytes": collective_bytes(txt_f),
            "bwd_count": collective_counts(txt_b).get("collective-permute", 0),
            "bwd_bytes": collective_bytes(txt_b),
        }
    # the ring must actually ride collective-permute
    assert stats["zigzag"]["fwd_count"] >= N_RING - 1, stats
    assert stats["zigzag"]["bwd_count"] >= N_RING - 1, stats
    assert stats["zigzag"] == stats["masked"], stats


# ------------------------------------------------------------------ engine config
def test_engine_sequence_parallel_config_block(mesh):
    """The ``sequence_parallel`` config block wires the model's sequence-parallel
    loss build into the engine: pass the MODEL OBJECT (not a pre-built model_fn)
    plus the block, and ``engine.model_fn`` becomes the zigzag-ring loss —
    numerically equal to the dense ``model.apply`` on natural-order inputs.
    (Training THROUGH this exact loss build is already exercised by
    test_gpt2_sequence_parallel_trains_through_engine; recompiling a second
    fused engine step here would double tier-1's slowest compile for no new
    coverage, so this test stops at the wiring + loss parity.)"""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32, n_layer=1, n_head=2,
                     compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(1))
    engine = DeepSpeedEngine(
        model=model, model_parameters=params, mesh=mesh,
        config_params={"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
                       "gradient_accumulation_steps": 1, "steps_per_print": 100,
                       "sequence_parallel": {"enabled": True, "schedule": "zigzag"},
                       "optimizer": {"type": "Adam", "params": {"lr": 3e-3}}})
    rng = np.random.default_rng(5)
    toks = jnp.asarray(rng.integers(0, 64, size=(2, 64)).astype(np.int32))
    labels = jnp.roll(toks, -1, axis=1)
    # the block must have swapped model_fn for the RING loss: its program rides
    # collective-permute (plain model.apply has no collectives at all), while
    # the loss value still equals the dense model on natural-order inputs
    lowered = jax.jit(engine.model_fn).lower(params, toks, labels)
    assert "collective_permute" in lowered.as_text()  # stablehlo spelling
    l_sp = float(lowered.compile()(params, toks, labels))
    l_ref = float(model.apply(params, toks, labels))
    np.testing.assert_allclose(l_sp, l_ref, rtol=2e-5)


def test_engine_sequence_parallel_requires_capable_model():
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    with pytest.raises(TypeError, match="sequence_parallel"):
        DeepSpeedEngine(
            model=lambda p, x: jnp.sum(p * x), model_parameters=jnp.ones((4,)),
            config_params={"train_batch_size": 8,
                           "sequence_parallel": {"enabled": True},
                           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
