"""Numerics observatory tests (docs/numerics.md).

Covers the four pieces and their core guarantees:
  - numerics DISABLED (the default) leaves the compiled step program
    HLO-instruction-identical — the sentinel is a trace-time branch, not a
    runtime one;
  - numerics ENABLED adds no collectives to the step (the per-subtree
    segment-sum replaces the scalar global-norm reduction 1:1) and no host
    sync beyond the loss fetch (enforced statically by test_no_sync_guard.py);
  - overflow is localized to a named parameter subtree;
  - the loss-scale journal replays the device scaler exactly;
  - the cross-rank desync audit runs only on audit steps and flags nothing on
    a healthy replicated run;
  - the flight recorder dumps a parseable post-mortem bundle.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.fp16.fused_optimizer import FP16_Optimizer
from deepspeed_tpu.utils.hlo import (collective_counts, instruction_count,
                                     optimized_hlo)
from deepspeed_tpu.utils.numerics import (FlightRecorder, build_subtree_index,
                                          compare_audit_rows, subtree_name)
from simple_model import SimpleModel, random_dataset, simple_config

HIDDEN = 16


def _build(**overrides):
    model = SimpleModel(HIDDEN)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
        config_params=simple_config(**overrides))
    return eng


def _batch(n=8, seed=0):
    data = random_dataset(n, HIDDEN, seed=seed)
    return (np.stack([d[0] for d in data]), np.stack([d[1] for d in data]))


def _run_steps(eng, steps, n=8):
    xs, ys = _batch(n)
    for _ in range(steps):
        loss = eng(xs, ys)
        eng.backward(loss)
        eng.step()


def _poison(eng, key="w2"):
    """Overwrite one accumulated-gradient subtree with NaN between backward
    and step — a localized overflow the sentinel must attribute to ``key``."""
    g = dict(eng._grad_acc)
    leaf = g[key]
    g[key] = jax.device_put(jnp.full(leaf.shape, jnp.nan, leaf.dtype), leaf.sharding)
    eng._grad_acc = g


def _apply_update_hlo(eng):
    grads = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, eng._acc_dtype), eng.params)
    step = jnp.asarray(1, jnp.int32)
    hyper = eng.optimizer.current_hyper()
    return optimized_hlo(eng._jit_apply_update, eng.master_params, eng.opt_state,
                         eng.scaler_state, grads, eng.params, step, hyper)


# --------------------------------------------------------------- HLO identity
def test_disabled_step_program_hlo_identical():
    """The numerics block absent and {"enabled": false} must compile the very
    same step program: the sentinel is gated at trace time (a captured Python
    None), so disabled mode cannot perturb what XLA sees."""
    base = _build()
    off = _build(numerics={"enabled": False})
    h_base, h_off = _apply_update_hlo(base), _apply_update_hlo(off)
    assert instruction_count(h_base) == instruction_count(h_off)
    assert collective_counts(h_base) == collective_counts(h_off)


def test_enabled_adds_no_collectives():
    """The per-subtree segment-sum replaces the scalar global-norm reduction
    1:1: turning the sentinel on must not change the step's collective set,
    and must leave the forward/backward program untouched entirely."""
    off = _build()
    on = _build(numerics={"enabled": True})
    assert collective_counts(_apply_update_hlo(off)) == \
        collective_counts(_apply_update_hlo(on))
    xs, ys = _batch()
    fwd_off = optimized_hlo(off._jit_loss_and_grad, off.params,
                            off.scaler_state.cur_scale, xs, ys)
    fwd_on = optimized_hlo(on._jit_loss_and_grad, on.params,
                           on.scaler_state.cur_scale, xs, ys)
    assert instruction_count(fwd_off) == instruction_count(fwd_on)


# --------------------------------------------------------------- sentinel
def test_sentinel_reports_per_subtree_stats():
    eng = _build(numerics={"enabled": True})
    _run_steps(eng, 2)
    rec = eng._numerics.last_record
    assert rec["step"] == 2
    assert sorted(rec["subtrees"]) == ["b1", "b2", "w1", "w2"]
    assert all(v >= 0 for v in rec["grad_norm_per_subtree"])
    assert all(v > 0 for v in rec["weight_norm_per_subtree"])
    assert rec["nonfinite_total"] == 0 and rec["anomaly"] is None
    # derived global norm agrees with the engine's own scalar
    assert np.isclose(rec["grad_norm"],
                      float(jax.device_get(eng._last_grad_norm)), rtol=1e-5)


def test_sentinel_localizes_overflow_to_subtree():
    eng = _build(fp16={"enabled": True, "initial_scale_power": 4},
                 numerics={"enabled": True})
    xs, ys = _batch()
    loss = eng(xs, ys)
    eng.backward(loss)
    _poison(eng, "w2")
    eng.step()
    assert eng.skipped_steps == 1
    rec = eng._numerics.last_record
    assert rec["overflow"] is True
    assert rec["anomaly"]["kind"] == "nonfinite_grad"
    assert rec["anomaly"]["subtree"] == "w2"
    per = dict(zip(rec["subtrees"], rec["nonfinite_per_subtree"]))
    assert per["w2"] > 0
    assert per["w1"] == per["b1"] == per["b2"] == 0


def test_sentinel_works_on_fused_step_path():
    eng = _build(fused_step=True, numerics={"enabled": True})
    _run_steps(eng, 2)
    rec = eng._numerics.last_record
    assert rec["step"] == 2 and sorted(rec["subtrees"]) == ["b1", "b2", "w1", "w2"]
    assert rec["grad_norm"] is not None and rec["grad_norm"] > 0


def test_sentinel_works_on_offload_path():
    cfg = {"zero_optimization": {"stage": 2, "cpu_offload": True},
           "fp16": {"enabled": True, "initial_scale_power": 4},
           "numerics": {"enabled": True}}
    eng = _build(**cfg)
    assert eng._offload is not None
    xs, ys = _batch()
    loss = eng(xs, ys)
    eng.backward(loss)
    _poison(eng, "w2")
    eng.step()
    assert eng.skipped_steps == 1
    rec = eng._numerics.last_record
    assert rec["anomaly"]["subtree"] == "w2"


def test_overflow_dedup_standard_and_offload_agree():
    """Satellite: the three historical overflow checks now share ONE helper
    (runtime/utils.detect_overflow); both engine branches must reach the same
    verdict and the same offending subtree on the same crafted overflow."""
    std = _build(fp16={"enabled": True, "initial_scale_power": 4},
                 numerics={"enabled": True})
    off = _build(zero_optimization={"stage": 2, "cpu_offload": True},
                 fp16={"enabled": True, "initial_scale_power": 4},
                 numerics={"enabled": True})
    xs, ys = _batch()
    for eng in (std, off):
        loss = eng(xs, ys)
        eng.backward(loss)
        _poison(eng, "w1")
        eng.step()
    assert std.skipped_steps == off.skipped_steps == 1
    assert std._numerics.last_record["anomaly"]["subtree"] == "w1"
    assert off._numerics.last_record["anomaly"]["subtree"] == "w1"


def test_fp16_optimizer_overflow_and_journal():
    """Satellite: the standalone FP16_Optimizer shares detect_overflow and
    carries its own loss-scale journal."""
    model = SimpleModel(HIDDEN)
    params = model.init(jax.random.PRNGKey(0))
    opt = FP16_Optimizer(params, optimizer="adam", initial_scale_power=4,
                         hysteresis=1)
    nan_grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, jnp.nan, jnp.float32), params)
    opt.step(nan_grads)
    assert opt.overflow is True
    assert opt.journal.cur_scale == opt.cur_scale
    assert [e["kind"] for e in opt.journal.events] == ["backoff", "skip"]


# --------------------------------------------------------------- journal
def test_journal_replays_device_scaler_exactly():
    eng = _build(fp16={"enabled": True, "initial_scale_power": 4,
                       "loss_scale_window": 2, "hysteresis": 1},
                 numerics={"enabled": True})
    xs, ys = _batch()
    for i in range(6):
        loss = eng(xs, ys)
        eng.backward(loss)
        if i in (2, 3):
            _poison(eng, "w1")
        eng.step()
        assert eng._numerics.journal.cur_scale == float(eng.loss_scale()), \
            f"journal desynced from device scaler at step {i}"
    kinds = [e["kind"] for e in eng._numerics.journal.events]
    assert "ramp" in kinds and "backoff" in kinds and "skip" in kinds
    assert "recovered" in kinds  # the clean step after the poisoned streak


def test_journal_min_scale_floor_and_streak():
    from deepspeed_tpu.runtime.fp16.loss_scaler import LossScaleJournal
    j = LossScaleJournal(dynamic=True, init_scale=4.0, scale_window=1000,
                        min_scale=1.0, hysteresis=1)
    for s in range(1, 4):
        j.record(s, True)
    kinds = [e["kind"] for e in j.events]
    assert j.cur_scale == 1.0
    assert "min_scale_floor" in kinds
    assert j.skip_streak == 3
    assert [e["streak"] for e in j.events if e["kind"] == "skip"] == [1, 2, 3]


# --------------------------------------------------------------- desync audit
def test_audit_runs_on_schedule_and_is_clean(tmp_path):
    eng = _build(numerics={"enabled": True, "audit_interval": 2},
                 tensorboard={"enabled": True, "output_path": str(tmp_path),
                              "job_name": "aud"})
    _run_steps(eng, 4)
    num = eng._numerics
    assert num.audit_runs == 2          # steps 2 and 4 only
    assert num.desync is None
    assert num.audit_seconds > 0
    eng.monitor.close()
    events = [json.loads(l) for l in
              open(os.path.join(str(tmp_path), "aud", "events.jsonl"))]
    audits = [e for e in events if e["event"] == "desync_audit"]
    assert len(audits) == 2
    assert all(e["payload"]["divergence"] is None for e in audits)
    assert all(e["payload"]["replicas"] == eng.dp_size for e in audits)


def test_audit_covers_params_and_optimizer_state():
    eng = _build(numerics={"enabled": True, "audit_interval": 1})
    _run_steps(eng, 1)
    assert eng._audit_fn_cached not in (None, False)
    _, names = eng._audit_fn_cached
    assert any(n.startswith("params/") for n in names)
    assert any(n.startswith("opt/") for n in names)


def test_no_audit_collectives_off_schedule():
    """Extra collectives appear ONLY on audit steps: the audit is a separate
    jitted program, never fused into the step."""
    eng = _build(numerics={"enabled": True, "audit_interval": 3})
    _run_steps(eng, 2)
    assert eng._numerics.audit_runs == 0        # not due yet
    assert eng._audit_fn_cached is None         # never even compiled
    _run_steps(eng, 1)
    assert eng._numerics.audit_runs == 1


def test_compare_audit_rows():
    names = ["a", "b", "c"]
    clean = np.asarray([[1, 2, 3], [1, 2, 3]], np.uint32)
    assert compare_audit_rows(clean, names) is None
    bad = np.asarray([[1, 2, 3], [1, 9, 3], [1, 2, 3]], np.uint32)
    d = compare_audit_rows(bad, names)
    assert d["subtree"] == "b" and d["index"] == 1
    assert d["diverging_replicas"] == [1]
    assert compare_audit_rows(np.asarray([[1, 2]], np.uint32), ["a", "b"]) is None


# --------------------------------------------------------------- flight recorder
def test_flight_recorder_ring_is_bounded_and_dumps(tmp_path):
    rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    for s in range(10):
        rec.record_step({"step": s, "overflow": False, "loss_scale": 2.0 ** s,
                         "anomaly": None})
    rec.record_event("loss_scale", {"kind": "ramp"}, step=9)
    assert len(rec.steps) == 4                      # ring stayed bounded
    assert rec.steps[0]["step"] == 6
    rec.note_anomaly()
    path = rec.trigger("test_reason", {"why": "unit test"})
    assert path and os.path.exists(path)
    bundle = json.load(open(path))
    assert bundle["reason"] == "test_reason"
    assert bundle["loss_scale_trajectory"][-1] == [9, 2.0 ** 9]
    assert [s["step"] for s in bundle["steps"]] == [6, 7, 8, 9]
    assert bundle["events"][0]["event"] == "loss_scale"


def test_flight_recorder_first_bad_step(tmp_path):
    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
    rec.record_step({"step": 1, "overflow": False, "anomaly": None})
    rec.record_step({"step": 2, "overflow": True,
                     "anomaly": {"kind": "nonfinite_grad", "subtree": "w2"}})
    rec.record_step({"step": 3, "overflow": True,
                     "anomaly": {"kind": "nonfinite_grad", "subtree": "w2"}})
    bad = rec.first_bad_step()
    assert bad["step"] == 2
    bundle = rec.bundle("r", None)
    assert bundle["first_bad_step"] == 2
    assert bundle["offending_subtree"] == "w2"


def test_consecutive_skip_streak_triggers_dump(tmp_path):
    eng = _build(fp16={"enabled": True, "initial_scale_power": 4},
                 numerics={"enabled": True, "consecutive_skip_trigger": 2,
                           "dump_dir": str(tmp_path)})
    xs, ys = _batch()
    for _ in range(2):
        loss = eng(xs, ys)
        eng.backward(loss)
        _poison(eng, "b1")
        eng.step()
    rec = eng._numerics.recorder
    assert rec.dump_count == 1
    bundle = json.load(open(rec.last_dump_path))
    assert bundle["reason"] == "consecutive_overflow_skips"
    assert bundle["offending_subtree"] == "b1"


# --------------------------------------------------------------- helpers
def test_build_subtree_index_and_names():
    tree = {"w1": jnp.ones((2, 2)), "blk": {"a": jnp.ones((3,)), "b": jnp.ones((3,))}}
    idx = build_subtree_index(tree, depth=1)
    assert sorted(idx.names) == ["blk", "w1"]
    assert idx.n == 2
    assert len(idx.leaf_buckets) == 3   # one entry per leaf


def test_subtree_name_depths():
    tree = {"blk": {"a": jnp.ones((3,))}}
    (path, _), = jax.tree_util.tree_flatten_with_path(tree)[0]
    assert subtree_name(path, 1) == "blk"
    assert subtree_name(path, 2) == "blk/a"
    assert subtree_name((), 1) == "<root>"
