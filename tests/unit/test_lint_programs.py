"""Program-pass lint: seeded violations, golden report, registry clean gate.

Three fixtures each break exactly one invariant the program passes exist to
catch, and the full report over all three is pinned byte-for-byte against
``tests/unit/golden/lint_seeded_violations.json`` — the report format is a
contract (CI parses it), so a formatting or ordering change must show up as
a golden diff, not silently.

The clean gate at the bottom is the tier-1 CI hook for `ds-tpu lint`: the
shipped registry must produce zero non-allowlisted violations.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.lint.model import Allowlist, LintReport
from deepspeed_tpu.lint.program_passes import (ProgramArtifact,
                                               run_program_passes)
from deepspeed_tpu.parallel.mesh import DATA_AXIS, build_mesh

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden",
                      "lint_seeded_violations.json")


# ----------------------------------------------------------------- fixtures
def seeded_broken_donation():
    """Donates a buffer XLA cannot alias: the donated f32 input only flows
    into a bf16 output (half the bytes), so the donation is a no-op and the
    donation pass must call it out."""
    f = jax.jit(lambda x: (x * 2).astype(jnp.bfloat16), donate_argnums=(0,))
    x = jnp.ones((64, 64), jnp.float32)
    manifest = {"donation": {"check_unusable": True}, "strict": True}
    return ProgramArtifact.capture("seeded_broken_donation", f, (x,), manifest)


def seeded_full_gather():
    """A ZeRO-style program whose output sharding silently re-replicates a
    data-sharded input: the partitioner must emit a full-param all-gather,
    and the strict manifest (which budgets only the reduction) flags it as
    undeclared."""
    mesh = build_mesh(data=8)
    sharded = NamedSharding(mesh, P(DATA_AXIS))
    replicated = NamedSharding(mesh, P())

    @lambda fn: jax.jit(fn, out_shardings=(replicated, replicated))
    def step(w, g):
        # the reduction every manifest expects... plus the injected gather:
        # the replicated out_sharding on `w_new` forces gathering the sharded w
        gsum = jax.lax.with_sharding_constraint(g, replicated)
        return w - 0.1 * gsum, gsum

    w = jax.device_put(np.ones((4096,), np.float32), sharded)
    g = jax.device_put(np.ones((4096,), np.float32), replicated)
    manifest = {"collectives": {}, "strict": True,
                "donation": {"check_unusable": False}}
    return ProgramArtifact.capture("seeded_full_gather", step, (w, g), manifest)


def seeded_fp32_leak():
    """A bf16 MLP with one mid-chain .astype(f32) matmul — the silent
    promotion the dtype pass exists to catch (the dot runs off the
    low-precision MXU path and doubles its flops and activation bytes)."""
    @jax.jit
    def f(w1, w2, x):
        h = jnp.tanh(x @ w1)
        h32 = h.astype(jnp.float32)          # the leak
        out = h32 @ w2.astype(jnp.float32)
        return out.astype(jnp.bfloat16)

    w = jnp.ones((32, 32), jnp.bfloat16)
    x = jnp.ones((8, 32), jnp.bfloat16)
    manifest = {"compute_dtype": "bf16", "strict": True,
                "donation": {"check_unusable": False}}
    return ProgramArtifact.capture("seeded_fp32_leak", f, (w, w, x), manifest)


def _seeded_report():
    artifacts = [seeded_broken_donation(), seeded_full_gather(),
                 seeded_fp32_leak()]
    report = LintReport()
    report.programs += [a.name for a in artifacts]
    report.extend(run_program_passes(artifacts))
    report.finish()
    return report


# ------------------------------------------------------- per-fixture checks
def test_broken_donation_is_caught_by_the_donation_pass():
    vs = run_program_passes([seeded_broken_donation()])
    vids = {v.vid for v in vs}
    assert "program-donation:unusable-donation:seeded_broken_donation#arg0" in vids
    assert all(v.pass_id == "program-donation" for v in vs), vids


def test_injected_all_gather_is_caught_as_undeclared_collective():
    vs = run_program_passes([seeded_full_gather()])
    vids = {v.vid for v in vs}
    assert ("program-collectives:undeclared-collective:"
            "seeded_full_gather#all-gather") in vids


def test_fp32_leak_is_caught_by_the_dtype_pass():
    vs = run_program_passes([seeded_fp32_leak()])
    by_rule = {}
    for v in vs:
        by_rule.setdefault(v.rule, []).append(v)
    assert "f32-dot-in-lowp-region" in by_rule, {v.vid for v in vs}
    assert by_rule["f32-dot-in-lowp-region"][0].subject == "seeded_fp32_leak#dot0"


def test_each_fixture_trips_only_its_own_pass():
    """Seeds must be surgical: fixture A's violation set never bleeds into
    pass B (that would mean the passes overlap and vids are ambiguous)."""
    expected_pass = {"seeded_broken_donation": "program-donation",
                     "seeded_full_gather": "program-collectives",
                     "seeded_fp32_leak": "program-dtype"}
    for fixture, pass_id in expected_pass.items():
        art = {"seeded_broken_donation": seeded_broken_donation,
               "seeded_full_gather": seeded_full_gather,
               "seeded_fp32_leak": seeded_fp32_leak}[fixture]()
        for v in run_program_passes([art]):
            assert v.pass_id == pass_id, f"{fixture} leaked into {v.vid}"


# ------------------------------------------------------------------- golden
def test_seeded_report_matches_golden_bytes():
    """The full JSON report over all three seeds, byte-for-byte. Regenerate
    with: python tests/unit/test_lint_programs.py --regen"""
    text = _seeded_report().to_json()
    with open(GOLDEN) as f:
        golden = f.read()
    assert text == golden, "lint report drifted from golden (see --regen)"


def test_seeded_report_is_deterministic_across_runs():
    assert _seeded_report().to_json() == _seeded_report().to_json()


# -------------------------------------------------------- registry clean gate
def test_shipped_registry_lints_clean():
    """THE CI gate: every program on every registry engine's active step path
    passes donation/collective/dtype lint with zero non-allowlisted
    violations, and no shipped allowlist entry is stale on the program side."""
    from deepspeed_tpu.lint import registry
    from deepspeed_tpu.lint.cli import _DEFAULT_ALLOWLIST

    allowlist = Allowlist.load(_DEFAULT_ALLOWLIST)
    report = LintReport()
    for entry in sorted(registry.BUILDERS):
        artifacts = registry.capture_entry(entry)
        assert artifacts, f"registry entry {entry} produced no programs"
        report.programs += [a.name for a in artifacts]
        report.extend(run_program_passes(artifacts), allowlist)
    report.finish(allowlist)
    assert not report.failed, "\n".join(
        f"{v.vid}: {v.message}" for v in report.violations)


if __name__ == "__main__":
    import sys
    if "--regen" in sys.argv:
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        with open(GOLDEN, "w") as f:
            f.write(_seeded_report().to_json())
        print(f"wrote {GOLDEN}")
