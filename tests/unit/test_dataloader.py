"""DeepSpeedDataLoader / RepeatingLoader tests (reference dataloader.py:10-101 semantics
adapted to the single-controller model: loaders yield GLOBAL micro-batches; the engine's
data-axis sharding performs the per-rank split)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader, RepeatingLoader,
                                              _default_collate)


def _dataset(n=10, dim=3):
    return [(np.full((dim,), i, np.float32), np.int32(i)) for i in range(n)]


def test_batching_and_len():
    dl = DeepSpeedDataLoader(_dataset(10), batch_size=4)       # drop_last default
    assert len(dl) == 2
    batches = list(dl)
    assert len(batches) == 2
    xs, ys = batches[0]
    assert xs.shape == (4, 3) and ys.shape == (4,)
    np.testing.assert_array_equal(ys, [0, 1, 2, 3])


def test_drop_last_false_keeps_tail():
    dl = DeepSpeedDataLoader(_dataset(10), batch_size=4, drop_last=False)
    assert len(dl) == 3
    tail = list(dl)[-1]
    assert tail[0].shape[0] == 2


def test_shuffle_is_seeded_and_reshuffles_per_epoch():
    ds = _dataset(16)
    a = [b[1].tolist() for b in DeepSpeedDataLoader(ds, 4, shuffle=True, seed=7)]
    b = [b[1].tolist() for b in DeepSpeedDataLoader(ds, 4, shuffle=True, seed=7)]
    assert a == b, "same seed + epoch must give the same order"
    dl = DeepSpeedDataLoader(ds, 4, shuffle=True, seed=7)
    e1 = [bb[1].tolist() for bb in dl]
    e2 = [bb[1].tolist() for bb in dl]
    assert e1 != e2, "epochs must reshuffle"
    assert sorted(sum(e1, [])) == sorted(sum(e2, [])) == list(range(16))


def test_repeating_loader_wraps_around():
    dl = DeepSpeedDataLoader(_dataset(8), batch_size=4)
    rep = RepeatingLoader(dl)
    got = [next(rep)[1].tolist() for _ in range(5)]            # 2 batches/epoch -> wraps
    assert len(got) == 5
    assert got[0] == got[2] or got[0] == got[4] or True        # deterministic unshuffled:
    assert got[0] == [0, 1, 2, 3] and got[2] == [0, 1, 2, 3]


def test_default_collate_dict_and_scalar():
    out = _default_collate([{"a": np.ones(2), "b": 1}, {"a": np.zeros(2), "b": 2}])
    assert out["a"].shape == (2, 2) and out["b"].tolist() == [1, 2]
    out = _default_collate([np.ones(3), np.zeros(3)])
    assert out.shape == (2, 3)
