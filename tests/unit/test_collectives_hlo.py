"""Compiled-collective audit (VERDICT r3 #3).

The design stance throughout the framework is "XLA emits the collective the reference
called NCCL/MPI for" (zero/sharding.py vs stage2.py:682-745,1441-1472; pipeline_spmd /
ring_attention vs p2p.py; custom_collectives.py vs the MPI compressed allreduce).
On the one axis this environment cannot run for real — multi-chip — compiled-program
inspection is the available proxy: these tests lower the flagship multi-device
programs on the virtual 8-device mesh and assert the expected collective ops appear
in the optimized HLO, failing on regression.

Backend note: XLA's CPU pipeline does not run the all-reduce+dynamic-slice →
reduce-scatter rewrite the TPU pipeline applies, so ZeRO's gradient scatter shows up
as ``all-reduce`` + sharded outputs here; the assertion therefore checks BOTH the
reduction collective and the scattered output sharding (which is what forces the
TPU partitioner to emit reduce-scatter).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oldjax import grad_through_shard_map_xfail
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import DATA_AXIS, build_mesh
from deepspeed_tpu.utils.hlo import (collective_bytes, collective_counts,
                                     collective_result_types,
                                     optimized_hlo as optimized_text)

from simple_model import SimpleModel, simple_config


# --------------------------------------------------------------------------- ZeRO-2
def test_zero2_train_step_reduces_and_scatters_grads():
    """ZeRO-2: the grad path must cross the data axis with a reduction collective and
    STORE grads scattered (per-rank partitions — reference stage2.py:682-745), and
    the update must all-gather the new params (stage2.py:1441-1472)."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    model = SimpleModel(64)
    eng = DeepSpeedEngine(model=model, model_parameters=model.init(jax.random.PRNGKey(0)),
                          config_params=simple_config(batch=8,
                                                      zero_optimization={"stage": 2}))
    x = jnp.ones((8, 64))
    y = jnp.ones((8, 64))
    txt = optimized_text(eng._jit_loss_and_grad, eng.params,
                         eng.scaler_state.cur_scale, x, y)
    counts = collective_counts(txt)
    assert counts.get("reduce-scatter", 0) + counts.get("all-reduce", 0) >= 1, \
        f"no cross-data grad reduction in the ZeRO-2 backward: {counts}"
    # grads leave the jit scattered over 'data' (this sharding is what makes the TPU
    # partitioner emit reduce-scatter instead of all-reduce)
    scattered = sum(not s.is_fully_replicated
                    for s in jax.tree_util.tree_leaves(eng._grad_shardings))
    assert scattered >= 2, "ZeRO-2 grad shardings are not scattered"

    # optimizer update: scattered master -> replicated compute params needs all-gather
    grads = jax.device_put(
        jax.tree_util.tree_map(lambda a: jnp.zeros(a.shape, eng._acc_dtype),
                               eng.master_params),
        eng._grad_shardings)
    step = jnp.asarray(1, jnp.int32)
    txt2 = optimized_text(eng._jit_apply_update, eng.master_params, eng.opt_state,
                          eng.scaler_state, grads, eng.params, step,
                          eng.optimizer.current_hyper())
    counts2 = collective_counts(txt2)
    assert counts2.get("all-gather", 0) >= 1, \
        f"no all-gather re-materializing params from ZeRO partitions: {counts2}"


# --------------------------------------------------------------------------- ring
def test_ring_attention_emits_collective_permute():
    from deepspeed_tpu.parallel.ring_attention import ring_attention_sharded

    mesh = build_mesh(data=8)
    q = jnp.zeros((1, 2, 256, 32), jnp.float32)
    j = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh, causal=True,
                                                       interpret=True))
    txt = optimized_text(j, q, q, q)
    counts = collective_counts(txt)
    assert counts.get("collective-permute", 0) >= 7, \
        f"8-rank ring should rotate k/v via collective-permute: {counts}"

    # the backward ring too: ppermute transposes to the reverse rotation
    g = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
        ring_attention_sharded(q, k, v, mesh, interpret=True) ** 2), argnums=(0, 1, 2)))
    txt_b = optimized_text(g, q, q, q)
    assert collective_counts(txt_b).get("collective-permute", 0) >= 7


# --------------------------------------------------------------------------- pipeline
@grad_through_shard_map_xfail
def test_public_api_pipeline_train_step_emits_collective_permute():
    """deepspeed.initialize(model=PipelineModule) routes homogeneous stages onto the
    SPMD executor: the jitted train step must move activations over the pipe axis
    with collective-permute (the reference's p2p.send/recv, pipe/p2p.py)."""
    import deepspeed_tpu
    from deepspeed_tpu.parallel.pipe import LayerSpec, PipelineModule

    class Linear:
        def __init__(self, dim):
            self.dim = dim

        def init(self, rng, x):
            return {"w": jax.random.normal(rng, (x.shape[-1], self.dim),
                                           jnp.float32) * 0.3}

        def apply(self, p, x):
            return jnp.tanh(x @ p["w"].astype(x.dtype))

    def mse(out, tgt):
        return jnp.mean(jnp.square(out - tgt))

    module = PipelineModule(layers=[LayerSpec(Linear, 16) for _ in range(4)],
                            num_stages=4, loss_fn=mse)
    params = module.init_params(jax.random.PRNGKey(0), jnp.zeros((4, 16)))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config_params={"train_batch_size": 16, "gradient_accumulation_steps": 4,
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert engine._spmd, "homogeneous 4-stage stack must route onto the SPMD executor"
    x = jax.device_put(np.zeros((4, 4, 16), np.float32),
                       NamedSharding(engine.mesh, P(None, DATA_AXIS)))
    txt = optimized_text(engine._jit_loss_and_grad, engine.params,
                         engine.scaler_state.cur_scale, x, x)
    counts = collective_counts(txt)
    assert counts.get("collective-permute", 0) >= 1, \
        f"SPMD pipeline train step has no collective-permute: {counts}"


# --------------------------------------------------------------------- 1-bit Adam
def test_compressed_allreduce_ships_packed_bits_on_the_wire():
    """The compressed allreduce's phase-1 exchange must be an all-to-all whose
    operand/result element type is u8 with n/8 elements — BIT-PACKED signs on
    the ICI wire (8 per byte), fp32 only after receipt (the reference shipped
    packed-bit cupy/MPI buffers, custom_collectives.py:23-50)."""
    from deepspeed_tpu.runtime.custom_collectives import compressed_allreduce

    mesh = build_mesh(data=8)
    n = 8 * 128
    x = jax.device_put(jnp.ones((8, n), jnp.float32),
                       NamedSharding(mesh, P(DATA_AXIS, None)))
    we = jax.device_put(jnp.zeros((8, n), jnp.float32),
                        NamedSharding(mesh, P(DATA_AXIS, None)))
    se = jax.device_put(jnp.zeros((8, n // 8), jnp.float32),
                        NamedSharding(mesh, P(DATA_AXIS, None)))
    j = jax.jit(lambda x, we, se: compressed_allreduce(mesh, x, we, se))
    txt = optimized_text(j, x, we, se)
    counts = collective_counts(txt)
    assert counts.get("all-to-all", 0) >= 1, f"no all-to-all in phase 1: {counts}"
    a2a_types = collective_result_types(txt, "all-to-all")
    assert a2a_types and set(a2a_types) == {"u8"}, \
        f"phase-1 all-to-all is not bit-packed uint8 on the wire: {a2a_types}"
    assert counts.get("all-gather", 0) >= 1, f"no phase-2 all-gather: {counts}"
    # phase-2 payload includes the packed server signs
    ag_types = collective_result_types(txt, "all-gather")
    assert "u8" in ag_types, f"phase-2 all-gather ships no packed payload: {ag_types}"


def test_sign_bit_packing_roundtrip():
    from deepspeed_tpu.runtime.custom_collectives import _pack_signs, _unpack_signs

    rng = np.random.default_rng(0)
    signs = jnp.asarray(rng.choice([-1, 1], size=(4, 256)).astype(np.int8))
    packed = _pack_signs(signs)
    assert packed.shape == (4, 32) and packed.dtype == jnp.uint8
    np.testing.assert_array_equal(np.asarray(_unpack_signs(packed)),
                                  np.asarray(signs))


def test_onebit_comm_volume_vs_fp32_allreduce():
    """Byte-accounting for the reference's headline '5x less communication'
    (README.md:18,37): signs ride the wire bit-packed (8/byte), so the sign
    payload is 32x under fp32 and the total — with the fp32 scale vectors —
    must beat the reference's 5x claim outright."""
    from deepspeed_tpu.runtime.custom_collectives import compressed_allreduce

    mesh = build_mesh(data=8)
    dp, n = 8, 64 * 1024
    sh = NamedSharding(mesh, P(DATA_AXIS, None))
    x = jax.device_put(jnp.ones((dp, n), jnp.float32), sh)
    we = jax.device_put(jnp.zeros((dp, n), jnp.float32), sh)
    se = jax.device_put(jnp.zeros((dp, n // dp), jnp.float32), sh)
    txt = optimized_text(jax.jit(lambda x, we, se: compressed_allreduce(mesh, x, we, se)),
                         x, we, se)
    compressed = collective_bytes(txt)

    # fp32 ring allreduce reference: reduce-scatter + all-gather, each (dp-1)/dp * 4n
    # bytes received per device => ~2 * 4n for large dp
    fp32_ring = 2 * (dp - 1) / dp * 4 * n
    ratio = fp32_ring / compressed
    # bit-packed signs: n/4 bytes total vs 7n fp32 -> ~28x; assert the claim-beating
    # floor with headroom for scale vectors and the replicated output gather
    assert ratio >= 10.0, (compressed, fp32_ring, ratio)
