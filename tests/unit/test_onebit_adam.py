"""1-bit Adam tests (analog of reference tests/onebitadam/test_com_reduce_*.py plus
optimizer-trajectory checks), on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.onebit_adam import OneBitAdam, OneBitAdamState
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.custom_collectives import compressed_allreduce, padded_size

from simple_model import SimpleModel, random_dataset, simple_config

DP = 8


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(model=1, pipe=1)


def test_padded_size():
    assert padded_size(1, 8) == 1024          # 8 * 128
    assert padded_size(1024, 8) == 1024
    assert padded_size(1025, 8) == 2048
    assert padded_size(4096, 4, lanes=128) == 4096


def test_compressed_allreduce_error_feedback_identity(mesh):
    """The error-feedback algebra must hold exactly:
    out = mean(x + we_old) - mean(we_new) + se_old - se_new   (per server chunk).
    This pins the two compression stages and both communication phases."""
    n = DP * 128
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(DP, n)), jnp.float32)
    we = jnp.asarray(rng.normal(size=(DP, n)) * 0.1, jnp.float32)
    se = jnp.asarray(rng.normal(size=(DP, n // DP)) * 0.1, jnp.float32)

    out, new_we, new_se = jax.jit(
        lambda x, we, se: compressed_allreduce(mesh, x, we, se))(x, we, se)
    out, new_we, new_se = map(np.asarray, (out, new_we, new_se))

    mean_corrected = np.mean(np.asarray(x) + np.asarray(we), axis=0)
    mean_new_we = np.mean(new_we, axis=0)
    server_in = mean_corrected - mean_new_we          # = mean of worker-compressed buffers
    # server chunk c lives on device c; reconstruct full-length old/new server errors
    se_full_old = np.asarray(se).reshape(-1)
    se_full_new = new_se.reshape(-1)
    expected = server_in + se_full_old - se_full_new
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_compressed_allreduce_error_feedback_converges(mesh):
    """Repeatedly reducing the same buffers, the running average of outputs converges to
    the true mean — the defining property of error-compensated compression."""
    n = DP * 128
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(DP, n)), jnp.float32)
    true_mean = np.mean(np.asarray(x), axis=0)
    we = jnp.zeros((DP, n), jnp.float32)
    se = jnp.zeros((DP, n // DP), jnp.float32)

    fn = jax.jit(lambda x, we, se: compressed_allreduce(mesh, x, we, se))
    outs = []
    for _ in range(40):
        out, we, se = fn(x, we, se)
        outs.append(np.asarray(out))
    rel = lambda v: np.linalg.norm(v - true_mean) / np.linalg.norm(true_mean)
    # Sign compression of gaussian data has a ~sqrt(1-2/pi)=0.60 single-shot error floor;
    # error feedback must drive the running average far below it (O(1/T) for the sum).
    assert rel(outs[0]) > 0.4, "sanity: single-shot compression should be crude"
    assert rel(np.mean(outs, axis=0)) < 0.15


def _stacked_like(tree, dp, rng):
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.normal(size=(dp,) + p.shape), jnp.float32) * 0.1, tree)


def test_warmup_matches_plain_adam_trajectory(mesh):
    """Before freeze_step the update must be exp_avg/(sqrt(exp_avg_sq)+eps) on the mean
    gradient (reference onebit_adam.py:320-324, 348-355 — no bias correction)."""
    rng = np.random.default_rng(2)
    params = {"w": jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)}
    opt = OneBitAdam(freeze_step=1000, dp_size=DP, mesh=mesh)
    state = opt.init(params)
    hyper = dict(lr=jnp.float32(0.01), beta1=jnp.float32(0.9), beta2=jnp.float32(0.999),
                 eps=jnp.float32(1e-8), weight_decay=jnp.float32(0.0))

    m_ref = np.zeros(32)
    v_ref = np.zeros(32)
    p_ref = np.asarray(params["w"]).reshape(-1).copy()
    apply = jax.jit(opt.apply)
    for step in range(1, 4):
        grads = _stacked_like(params, DP, rng)
        params, state = apply(grads, state, params, jnp.int32(step), hyper)
        g_mean = np.mean(np.asarray(grads["w"]), axis=0).reshape(-1)
        m_ref = 0.9 * m_ref + 0.1 * g_mean
        v_ref = 0.999 * v_ref + 0.001 * g_mean ** 2
        p_ref -= 0.01 * (m_ref / (np.sqrt(v_ref) + 1e-8))
        np.testing.assert_allclose(np.asarray(params["w"]).reshape(-1), p_ref,
                                   rtol=1e-5, atol=1e-6)


def test_frozen_phase_freezes_variance_and_compresses(mesh):
    rng = np.random.default_rng(3)
    params = {"w": jnp.asarray(rng.normal(size=(16, 16)), jnp.float32)}
    opt = OneBitAdam(freeze_step=2, dp_size=DP, mesh=mesh)
    state = opt.init(params)
    hyper = dict(lr=jnp.float32(0.01), beta1=jnp.float32(0.9), beta2=jnp.float32(0.999),
                 eps=jnp.float32(1e-8), weight_decay=jnp.float32(0.0))
    apply = jax.jit(opt.apply)
    for step in range(1, 3):  # warmup
        grads = _stacked_like(params, DP, rng)
        params, state = apply(grads, state, params, jnp.int32(step), hyper)
    v_frozen = np.asarray(state.exp_avg_sq).copy()
    assert np.all(np.asarray(state.worker_error) == 0)

    grads = _stacked_like(params, DP, rng)
    params, state = apply(grads, state, params, jnp.int32(3), hyper)
    np.testing.assert_array_equal(np.asarray(state.exp_avg_sq), v_frozen)
    assert np.any(np.asarray(state.worker_error) != 0), "compression must leave residuals"
    # frozen momentum is sign*scale per server chunk: few distinct magnitudes
    m = np.abs(np.asarray(state.exp_avg))
    assert len(np.unique(np.round(m, 6))) <= DP + 1


def test_onebit_elastic_checkpoint_dp_change(tmp_path):
    """Save under dp=8, resume under dp=4: moments carry over (truncated to the new
    padding) and the error-feedback buffers are re-chunked for the new topology —
    the accumulated residual survives instead of resetting to zero."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    def run_engine(mesh, load_dir=None):
        model = SimpleModel(hidden_dim=16)
        params = model.init(jax.random.PRNGKey(0))
        cfg = simple_config(batch=8)
        cfg["optimizer"] = {"type": "OneBitAdam", "params": {"lr": 1e-3, "freeze_step": 2}}
        eng = DeepSpeedEngine(model=model, model_parameters=params,
                              config_params=cfg, mesh=mesh)
        return eng

    data = random_dataset(64, 16)

    def steps(eng, n, start=0):
        for i in range(start, start + n):
            xs = np.stack([data[(i * 8 + j) % 64][0] for j in range(8)])
            ys = np.stack([data[(i * 8 + j) % 64][1] for j in range(8)])
            loss = eng(xs, ys)
            eng.backward(loss)
            eng.step()
        return float(jax.device_get(loss))

    eng8 = run_engine(build_mesh(model=1, pipe=1))
    steps(eng8, 6)  # crosses into frozen regime
    eng8.save_checkpoint(str(tmp_path), tag="elastic")

    mesh4 = build_mesh(data=4, model=1, pipe=1, devices=jax.devices()[:4])
    eng4 = run_engine(mesh4)
    eng4.load_checkpoint(str(tmp_path), tag="elastic")
    assert eng4.global_steps == eng8.global_steps
    # moments restored (nonzero); error-feedback residuals carried over, re-chunked
    assert np.any(np.asarray(eng4.opt_state.exp_avg) != 0)
    assert np.any(np.asarray(eng4.opt_state.worker_error) != 0), \
        "elastic restore must preserve the worker residual, not zero it"
    # server residual data region is a pure re-chunking of the dp=8 one: the two
    # reconstructed global vectors must agree bit-for-bit on the shared prefix
    def global_server(se, dp, n_pad):
        g = np.zeros(n_pad, np.float32)
        cs = n_pad // dp
        for d in range(dp):
            g[d * cs:(d + 1) * cs] = np.asarray(se)[d]
        return g
    se8 = np.asarray(eng8.opt_state.server_error)
    se4 = np.asarray(eng4.opt_state.server_error)
    g8 = global_server(se8, 8, se8.size)
    g4 = global_server(se4, 4, se4.size)
    n_model = sum(int(np.prod(p.shape))
                  for p in jax.tree_util.tree_leaves(eng8.params))
    np.testing.assert_array_equal(g4[:n_model], g8[:n_model])
    final = steps(eng4, 4, start=6)
    assert np.isfinite(final)


def test_elastic_adapt_round_trip_preserves_residuals(mesh):
    """dp=8 -> dp=4 -> dp=8: the server residual's real-data region must survive
    the round trip bit-for-bit (satellite: padded-tail handling across world-size
    change), and the worker residual's per-position mean — the only quantity the
    averaged output sees — must be preserved through each hop."""
    n = 1500  # paddings differ across dp: 2048 (dp=8) vs 1536 (dp=4)
    n8, n4 = padded_size(n, 8), padded_size(n, 4)
    assert n8 != n4
    rng = np.random.default_rng(7)
    state8 = {"exp_avg": rng.normal(size=n8).astype(np.float32),
              "exp_avg_sq": rng.normal(size=n8).astype(np.float32) ** 2,
              "worker_error": rng.normal(size=(8, n8)).astype(np.float32),
              "server_error": rng.normal(size=(8, n8 // 8)).astype(np.float32)}
    tmpl4 = {"exp_avg": np.zeros(n4, np.float32),
             "exp_avg_sq": np.zeros(n4, np.float32),
             "worker_error": np.zeros((4, n4), np.float32),
             "server_error": np.zeros((4, n4 // 4), np.float32)}
    tmpl8 = {k: np.zeros_like(a) for k, a in state8.items()}

    opt = OneBitAdam(freeze_step=1, dp_size=8, mesh=mesh)
    mid = opt.elastic_adapt(state8, tmpl4)
    back = opt.elastic_adapt(mid, tmpl8)

    np.testing.assert_array_equal(back["server_error"].reshape(-1)[:n],
                                  state8["server_error"].reshape(-1)[:n])
    np.testing.assert_allclose(
        back["worker_error"].mean(axis=0)[:n],
        state8["worker_error"].astype(np.float64).mean(axis=0)[:n],
        rtol=0, atol=1e-6)
    # moments: truncated to the smaller padding, data region preserved exactly
    np.testing.assert_array_equal(back["exp_avg"][:n], state8["exp_avg"][:n])
    assert np.all(back["exp_avg"][n4:] == 0)


def test_elastic_adapt_hierarchical_geometry(mesh):
    """Flat dp=8 residuals re-chunk onto a hierarchical dp=4 (2 slices of 2)
    template through the (d % L) * C + (d // L) * csize offset map, and the
    reconstructed global server vector matches the flat one on the data region."""
    from deepspeed_tpu.comm import derive_topology
    from deepspeed_tpu.ops.onebit_adam import OneBitAdam as OBA

    n = 1500
    n8, n4 = padded_size(n, 8), padded_size(n, 4)
    topo4 = derive_topology(4, 2)
    rng = np.random.default_rng(11)
    state8 = {"worker_error": rng.normal(size=(8, n8)).astype(np.float32),
              "server_error": rng.normal(size=(8, n8 // 8)).astype(np.float32)}
    tmpl4 = {"worker_error": np.zeros((4, n4 // 2), np.float32),   # L=2 chunking
             "server_error": np.zeros((4, n4 // 4), np.float32)}
    opt = OBA(freeze_step=1, dp_size=8, mesh=mesh)
    mid = opt.elastic_adapt(state8, tmpl4)
    assert mid["worker_error"].shape == (4, n4 // 2)

    # reassemble both global server residuals and compare the data region
    g8 = state8["server_error"].reshape(-1)
    g4 = np.zeros(n4, np.float32)
    cs4, C4 = n4 // 4, n4 // 2
    for d in range(4):
        off = (d % 2) * C4 + (d // 2) * cs4
        g4[off:off + cs4] = mid["server_error"][d]
    np.testing.assert_array_equal(g4[:n], g8[:n])


def test_onebit_hierarchical_matches_flat_convergence(mesh):
    """Frozen-phase averaging over a 2x4 factorized topology: the two-level
    compressed exchange tracks the true momentum no worse (plateau-wise) than
    the flat one, from the OneBitAdam apply() entry point. The instantaneous
    momentum sits at the single-shot sign-compression floor in both layouts —
    error feedback guarantees the time-average, so that is what must agree."""
    from deepspeed_tpu.comm import derive_topology

    rng = np.random.default_rng(5)
    params0 = {"w": jnp.asarray(rng.normal(size=(16, 16)), jnp.float32)}
    grads = _stacked_like(params0, DP, rng)
    g_mean = np.mean(np.asarray(grads["w"]), axis=0).reshape(-1)
    hyper = dict(lr=jnp.float32(0.01), beta1=jnp.float32(0.9), beta2=jnp.float32(0.999),
                 eps=jnp.float32(1e-8), weight_decay=jnp.float32(0.0))

    def run(topology):
        opt = OneBitAdam(freeze_step=1, dp_size=DP, mesh=mesh, topology=topology)
        state = opt.init(params0)
        if topology is not None:
            n_pad = state.exp_avg.shape[0]
            assert state.worker_error.shape == (DP, n_pad // topology.slice_size)
        apply = jax.jit(opt.apply)
        params = params0
        ms = []
        for step in range(1, 20):  # step 1 = warmup, rest frozen on fixed grads
            params, state = apply(grads, state, params, jnp.int32(step), hyper)
            ms.append(np.asarray(state.exp_avg)[:g_mean.size])
        assert np.any(np.asarray(state.worker_error) != 0)
        # EF contract: the running average of frozen-phase momenta approaches
        # the true (geometrically saturating) momentum far below the ~0.6
        # gaussian single-shot floor
        avg = np.mean(ms[4:], axis=0)
        tgt = np.mean([(1 - 0.9 ** k) * g_mean for k in range(5, 20)], axis=0)
        return np.linalg.norm(avg - tgt) / np.linalg.norm(tgt)

    rel_hier = run(derive_topology(DP, 2))
    rel_flat = run(None)
    assert rel_hier < 0.4, f"hierarchical EF time-average off: {rel_hier}"
    assert rel_hier < max(0.25, 1.5 * rel_flat), (rel_hier, rel_flat)


@pytest.mark.parametrize("freeze_step,lr,steps", [(100, 1e-2, 20), (10, 3e-3, 40)])
def test_engine_onebit_trains(freeze_step, lr, steps):
    """End-to-end: engine with optimizer type OneBitAdam drives the loss down, in both
    warmup (freeze_step > steps) and compressed regimes (freeze_step=10 < steps)."""
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0))
    cfg = simple_config(batch=16)
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": lr, "freeze_step": freeze_step}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config_params=cfg)
    data = random_dataset(320, 16)
    losses = []
    for i in range(steps):
        xs = np.stack([data[(i * 16 + j) % 320][0] for j in range(16)])
        ys = np.stack([data[(i * 16 + j) % 320][1] for j in range(16)])
        loss = engine(xs, ys)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.5, f"loss did not drop: {losses[0]} -> {losses[-1]}"
    if freeze_step < steps:  # the compressed phase itself must make progress
        assert losses[-1] < losses[freeze_step] * 0.8
