"""Scheduler determinism and policy contracts (host-only, plus one
engine-level replay).

The scheduler's determinism contract (module docstring of serve/scheduler.py)
is what makes preemption-by-recompute correct and serve-sim replayable:
every decision is a pure function of the submitted trace. These tests pin the
pieces — front-blocking FIFO admission, index-ordered slot/page hand-out,
latest-admitted-first preemption, restart bookkeeping — and then replay a
real engine trace twice, asserting the schedule logs and outputs serialize
byte-identically.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.serve.scheduler import Request, Scheduler


def _sched(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("num_blocks", 17)          # 16 usable
    kw.setdefault("block_size", 4)
    kw.setdefault("max_model_len", 32)
    kw.setdefault("prefill_chunk", 8)
    return Scheduler(**kw)


def test_submit_refuses_never_fit_requests():
    s = _sched()
    assert s.submit(Request("a", [1] * 8, 4)) is None
    assert "max_model_len" in s.submit(Request("b", [1] * 30, 8))
    assert "slots" in s.submit(Request("c", [1] * 4, 4, num_beams=5))
    # 4 beams x 8 blocks worst case > 16 usable pages: can never fit
    assert "pool" in s.submit(Request("d", [1] * 4, 28, num_beams=4))
    # refused requests never enter the queue
    assert len(s.waiting) == 1


def test_admission_is_fifo_front_blocking():
    """An unadmittable queue front blocks later arrivals — a small request
    arriving later must NOT overtake a big one stuck at the front."""
    s = _sched(num_slots=2)
    big = Request("big", [1] * 8, 4, num_beams=2, arrival=0)
    small = Request("small", [1] * 4, 4, arrival=1)
    s.submit(big)
    s.submit(small)
    # occupy one slot so `big` (needs 2) cannot be admitted
    s.submit(Request("holder", [1] * 4, 4, arrival=0))
    s.waiting.sort(key=lambda e: (e[0].arrival, e[1]))
    # admit order at it=0: holder only? No — holder was submitted last at
    # arrival 0, so FIFO admits big first... but big needs 2 slots and 2 are
    # free, so big and holder go in, small waits for a slot.
    admitted = [g.req.req_id for g in s.admit(0)]
    assert admitted == ["big"]               # 2 slots -> big takes both
    admitted = [g.req.req_id for g in s.admit(1)]
    assert admitted == []                    # holder blocks: no slots free
    s.finish_group(s.running[0])
    admitted = [g.req.req_id for g in s.admit(1)]
    assert admitted == ["holder", "small"]   # queue order preserved


def test_slots_and_pages_hand_out_in_index_order():
    s = _sched()
    g1 = s.admit(0)
    assert g1 == []
    s.submit(Request("a", [1] * 5, 4))
    s.submit(Request("b", [1] * 5, 4))
    ga, gb = s.admit(0)
    assert ga.slots == [0] and gb.slots == [1]
    assert ga.tables[0] == [1, 2] and gb.tables[0] == [3, 4]


def test_decode_write_block_allocation_and_fork_cow():
    s = _sched()
    s.submit(Request("a", [1] * 4, 8, num_beams=2))   # prompt fills block 0
    (g,) = s.admit(0)
    assert g.tables[0] == [1]
    s.finish_prefill_chunk(g, 4, 0)
    s.begin_decode(g, [7, 9], 0)
    assert g.tables[0] == [1] and g.tables[1] == [1]  # forked, shared
    preempted, copies = s.ensure_decode_room()
    assert preempted == []
    # pos 4 starts block 1: both lanes extend their (CoW-shared) tables
    assert len(g.tables[0]) == 2 and len(g.tables[1]) == 2
    assert g.tables[0][1] != g.tables[1][1]
    assert copies == []                               # fresh blocks, no copy
    # a mid-block write on a SHARED page triggers copy-on-write
    g.generated = [[7], [9]]
    s.reorder_beams(g, [0, 0])                        # both lanes from lane 0
    g.generated = [[7, 1], [7, 2]]                    # pos 5: same block 1
    preempted, copies = s.ensure_decode_room()
    assert preempted == []
    assert len(copies) == 1                           # one lane copied out
    assert g.tables[0][1] != g.tables[1][1]


def test_preemption_picks_latest_admitted_and_requeues_at_front_order():
    s = _sched(num_blocks=9)                          # 8 usable pages
    s.submit(Request("old", [1] * 8, 8))              # 2 prompt blocks
    s.submit(Request("new", [1] * 8, 8))
    g_old, g_new = s.admit(0)
    for g, tok in ((g_old, 3), (g_new, 4)):
        s.finish_prefill_chunk(g, 8, 0)
        s.begin_decode(g, [tok], 0)
    # drain the pool so decode-room allocation must preempt
    s.allocator.allocate(s.allocator.num_free)
    preempted, copies = s.ensure_decode_room()
    assert [g.req.req_id for g in preempted] == ["new"]
    assert g_new.preemptions == 1
    assert s.waiting[0][0].req_id == "new"            # requeued, FIFO position
    assert g_old in s.running and len(g_old.tables[0]) == 3


def test_schedule_is_a_pure_function_of_the_trace():
    """Two fresh schedulers fed the same trace of submit/admit/decode-room
    calls make byte-identical decisions."""
    def drive():
        s = _sched()
        log = []
        reqs = [Request("a", [1] * 6, 5), Request("b", [2] * 9, 4, arrival=1),
                Request("c", [3] * 4, 6, num_beams=2, arrival=1)]
        for r in reqs:
            log.append(("submit", r.req_id, s.submit(r)))
        for it in range(4):
            for g in s.admit(it):
                log.append(("admit", it, g.req.req_id, g.slots,
                            list(g.tables[0])))
            nxt = s.next_prefill(it)
            if nxt is not None:
                g, pos, n, chunk = nxt
                log.append(("prefill", it, g.req.req_id, pos, n, tuple(chunk)))
                if s.finish_prefill_chunk(g, n, it):
                    s.begin_decode(g, [5] * g.lanes, it)
            pre, copies = s.ensure_decode_room()
            log.append(("room", it, [g.req.req_id for g in pre],
                        list(copies)))
            for g, lane, slot in s.decode_lanes():
                if g.entered_decode_it != it:
                    g.generated[lane].append(6)
                    log.append(("decode", it, g.req.req_id, lane, slot))
        return json.dumps(log, default=str)

    assert drive() == drive()


def test_engine_trace_replays_byte_identically():
    """Full-stack determinism: the same request trace through two fresh
    engines produces byte-identical schedule logs and outputs."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.serve.engine import InferenceEngine
    from deepspeed_tpu.serve.sim import synth_trace

    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))

    def serialize():
        eng = InferenceEngine(model, params, num_slots=4, block_size=4,
                              num_blocks=21, max_model_len=32,
                              prefill_chunk=8)
        outs, logs = eng.run(synth_trace(8, vocab_size=64, max_model_len=32,
                                         seed=7))
        return json.dumps({
            "logs": logs,
            "outs": [(o.req_id, o.status, o.tokens, o.finished_it,
                      o.preemptions) for o in outs]})

    assert serialize() == serialize()


def test_synth_trace_poisson_arrivals():
    """Poisson mode: seeded-deterministic, non-decreasing integer arrivals
    whose mean inter-arrival tracks 1/rate, while the default path's trace
    stays byte-identical to a poisson-free build (separate RNG draws)."""
    from deepspeed_tpu.serve.sim import synth_trace

    kw = dict(vocab_size=64, max_model_len=32, seed=7)
    a = synth_trace(64, arrival_process=("poisson", 2.0), **kw)
    b = synth_trace(64, arrival_process=("poisson", 2.0), **kw)
    assert [r.arrival for r in a] == [r.arrival for r in b]
    arrivals = [r.arrival for r in a]
    assert arrivals == sorted(arrivals)
    assert all(isinstance(t, int) for t in arrivals)
    # 64 draws at rate 2/iter → span ≈ 32 iterations; loose 2x bounds
    assert 16 <= arrivals[-1] <= 64
    # a hotter rate compresses the same trace's span
    hot = [r.arrival for r in
           synth_trace(64, arrival_process=("poisson", 8.0), **kw)]
    assert hot[-1] < arrivals[-1]
    # default mode draws nothing extra: byte-equal with and without the arg
    d1 = synth_trace(8, **kw)
    d2 = synth_trace(8, arrival_process=None, **kw)
    assert [(r.req_id, r.arrival, r.prompt) for r in d1] == \
           [(r.req_id, r.arrival, r.prompt) for r in d2]

    with pytest.raises(ValueError, match="unknown arrival process"):
        synth_trace(4, arrival_process=("uniform", 1.0), **kw)
