"""FleetRouter contracts: routing, shedding, failover, conservation.

The router is a pure scheduling layer over N InferenceEngine replicas, so
every pinned property is deterministic: prefix-affinity sends a repeat
prefix back to the replica that cached it, round_robin cycles slots,
saturation sheds with status "shed" (refusal, not a crash), a mid-flight
kill with warm failover loses no requests and changes no tokens, and the
fleet-merged latency summary is bitwise-equal to a single-stream rebuild
over the concatenated ledgers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.engine import InferenceEngine
from deepspeed_tpu.serve.request_trace import LATENCY_METRICS, HistogramSketch
from deepspeed_tpu.serve.router import SHED_REASON, FleetRouter
from deepspeed_tpu.serve.scheduler import Request
from deepspeed_tpu.utils.cluster import fleet_latency_summary

ML = 32


@pytest.fixture(scope="module")
def model_and_params():
    cfg = GPT2Config(vocab_size=64, n_positions=ML, n_embd=16, n_layer=2,
                     n_head=2, compute_dtype=jnp.float32, loss_chunk=0)
    model = GPT2Model(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_and_params, slot, **kw):
    model, params = model_and_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 33)
    kw.setdefault("max_model_len", ML)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("request_trace", {"enabled": True, "capacity": 64,
                                    "host_id": slot})
    return InferenceEngine(model, params, **kw)


def _fleet(model_and_params, n, engine_kw=None, **router_kw):
    engines = [_engine(model_and_params, s, **(engine_kw or {}))
               for s in range(n)]
    return FleetRouter(engines, **router_kw)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(0, 64, size=n).astype(
        np.int32).tolist()


def _routed_slots(transcript):
    return {rid: slot for it in transcript["iterations"]
            for rid, slot, _ in it["routed"]}


# ------------------------------------------------------------ construction

def test_bad_policy_and_empty_fleet_raise(model_and_params):
    with pytest.raises(ValueError, match="policy"):
        _fleet(model_and_params, 1, policy="random")
    with pytest.raises(ValueError, match="at least one"):
        FleetRouter([])


# ----------------------------------------------------------------- routing

def test_round_robin_cycles_slots(model_and_params):
    router = _fleet(model_and_params, 2, policy="round_robin")
    reqs = [Request(f"r{i}", _prompt(i, 9), 3) for i in range(4)]
    outs, transcript = router.run(reqs)
    assert [o.status for o in outs] == ["finished"] * 4
    slots = _routed_slots(transcript)
    assert [slots[f"r{i}"] for i in range(4)] == [0, 1, 0, 1]


def test_affinity_routes_repeat_prefix_to_cached_replica(model_and_params):
    router = _fleet(model_and_params, 3, policy="affinity")
    base = _prompt(7, 16)
    # r0 seeds replica 0's prefix cache; r1/r2 keep the other replicas from
    # being trivially empty; r3 repeats r0's prompt after r0 has finished.
    reqs = [Request("r0", base, 3, arrival=0),
            Request("r1", _prompt(8, 16), 3, arrival=0),
            Request("r2", _prompt(9, 16), 3, arrival=0),
            Request("r3", base + [1, 2], 3, arrival=20)]
    outs, transcript = router.run(reqs)
    assert [o.status for o in outs] == ["finished"] * 4
    slots = _routed_slots(transcript)
    assert slots["r3"] == slots["r0"]
    hit = {rid: h for it in transcript["iterations"]
           for rid, _, h in it["routed"]}
    assert hit["r3"] > 0 and hit["r0"] == 0


def test_affinity_weight_zero_is_least_loaded(model_and_params):
    router = _fleet(model_and_params, 2, policy="affinity",
                    affinity_weight=0.0)
    base = _prompt(11, 16)
    reqs = [Request("r0", base, 3, arrival=0),
            Request("r1", base, 3, arrival=20),
            Request("r2", base, 3, arrival=20)]
    outs, transcript = router.run(reqs)
    slots = _routed_slots(transcript)
    # with weight 0 the cached prefix on slot 0 is worthless: r1 takes the
    # lowest-slot tie-break, r2 balances onto the other replica
    assert {slots["r1"], slots["r2"]} == {0, 1}


# ---------------------------------------------------------------- shedding

def test_max_queue_depth_sheds_with_refusal_semantics(model_and_params):
    router = _fleet(model_and_params, 1, policy="least_loaded",
                    max_queue_depth=2,
                    engine_kw={"num_slots": 1})
    reqs = [Request(f"r{i}", _prompt(20 + i, 9), 3) for i in range(8)]
    outs, _ = router.run(reqs)
    statuses = [o.status for o in outs]
    assert "shed" in statuses and "finished" in statuses
    for o in outs:
        if o.status == "shed":
            assert o.refusal == SHED_REASON and o.tokens == []
    # refusal, not a crash: every request got exactly one output, and the
    # front-door trace recorded every shed
    assert len(outs) == len(reqs)
    assert router.tracer.bundle()["counts"]["shed"] == statuses.count("shed")
    assert router.shed_count == statuses.count("shed")


def test_occupancy_cap_one_never_sheds(model_and_params):
    router = _fleet(model_and_params, 1, occupancy_cap=1.0)
    reqs = [Request(f"r{i}", _prompt(30 + i, 9), 3) for i in range(6)]
    outs, _ = router.run(reqs)
    assert [o.status for o in outs] == ["finished"] * 6


# ---------------------------------------------------------------- failover

def _run_with_kills(model_and_params, kills, cold, tmp_path):
    model, params = model_and_params

    def build_replacement(slot):
        return _engine(model_and_params, slot, telemetry=None)

    router = _fleet(model_and_params, 2,
                    kill_schedule=kills,
                    build_replacement=build_replacement,
                    snapshot_dir=str(tmp_path),
                    cold_failover=cold)
    reqs = [Request(f"r{i}", _prompt(40 + i, 12), 4, arrival=i)
            for i in range(8)]
    return router, router.run(reqs)


def test_warm_failover_conserves_requests_and_tokens(model_and_params,
                                                     tmp_path):
    _, (ref_outs, _) = _run_with_kills(model_and_params, [], False, tmp_path)
    router, (outs, transcript) = _run_with_kills(
        model_and_params, [(3, 0)], False, tmp_path)
    assert router.kills_applied == 1
    kills = [k for it in transcript["iterations"] for k in it["kills"]]
    assert kills == [[0, "warm"]]
    # no request lost, no token changed
    assert [o.status for o in outs] == ["finished"] * 8
    assert [o.tokens for o in outs] == [o.tokens for o in ref_outs]
    # the victim's finished records were retired into the ledger exactly once
    assert len(router.bundles()) == 2 + 1 + 1   # live + retired + front door


def test_cold_failover_reprefills_more_than_warm(model_and_params, tmp_path):
    warm, (wouts, _) = _run_with_kills(model_and_params, [(3, 0)], False,
                                       tmp_path)
    cold, (couts, _) = _run_with_kills(model_and_params, [(3, 0)], True,
                                       tmp_path)
    assert [o.tokens for o in wouts] == [o.tokens for o in couts]
    assert sum(warm.prefill_chunks) < sum(cold.prefill_chunks)


def test_kill_without_factory_raises(model_and_params):
    router = _fleet(model_and_params, 2, kill_schedule=[(0, 0)])
    with pytest.raises(RuntimeError, match="build_replacement"):
        router.run([Request("r0", _prompt(50, 9), 3)])


# ----------------------------------------------------------- observability

def test_fleet_summary_merge_is_exact(model_and_params):
    router = _fleet(model_and_params, 2)
    reqs = [Request(f"r{i}", _prompt(60 + i, 10), 3, arrival=i)
            for i in range(6)]
    router.run(reqs)
    summary = router.fleet_summary()
    bundles = router.bundles()
    assert summary["latency"] == fleet_latency_summary(bundles,
                                                       ps=(50, 95, 99))
    # bitwise-equal a single-stream rebuild over the concatenated ledgers
    singles = {m: HistogramSketch() for m in LATENCY_METRICS}
    for b in bundles:
        for rec in b["requests"]:
            if rec.get("status") == "finished":
                for m in LATENCY_METRICS:
                    singles[m].add(rec.get(m))
    single = {}
    for m in sorted(singles):
        if singles[m].count:
            for p in (50, 95, 99):
                single[f"{m}_p{p:g}"] = singles[m].percentile(p)
    assert summary["latency"] == single
    gp = summary["goodput_fleet"]
    assert 0.0 <= gp["goodput_fraction"] <= 1.0
    assert summary["serving"]["counts"]["finished"] == 6
    assert summary["finished"] == 6 and summary["shed"] == 0
