"""Real-TPU kernel parity smoke: compiled Pallas kernels vs dense XLA oracles.

The unit suite runs the kernels in interpret mode on a virtual CPU platform
(tests/conftest.py); this script validates the COMPILED TPU numerics and is meant to
gate perf rounds (run it before trusting bench numbers). Run directly:

    python tests/tpu_parity.py

Exits non-zero on any parity failure. Tolerances are set for the TPU's default fp32
matmul precision (bf16-pass dots), not CPU-exact fp32.
"""

import math
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

FAILURES = []


def check(name, got, want, tol):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    err = float(np.max(np.abs(got - want)))
    scale = float(np.max(np.abs(want))) or 1.0
    rel = err / scale
    ok = rel < tol
    print(f"{'PASS' if ok else 'FAIL'} {name}: max_abs_err={err:.3e} rel={rel:.3e} "
          f"(tol {tol})")
    if not ok:
        FAILURES.append(name)


def flash_checks():
    from deepspeed_tpu.ops.pallas.flash_attention import (
        flash_attention, dense_attention, dropout_keep_reference)
    B, H, T, D = 2, 4, 512, 64
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32) for _ in range(3))

    for causal in (False, True):
        out = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal))(q, k, v)
        ref = dense_attention(q, k, v, causal=causal)
        check(f"flash fwd causal={causal}", out, ref, 2e-2)
        gf = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal) ** 2), argnums=(0, 1, 2)))(q, k, v)
        gd = jax.grad(lambda q, k, v: jnp.sum(
            dense_attention(q, k, v, causal=causal) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(gf, gd, "qkv"):
            check(f"flash d{n} causal={causal}", a, b, 2e-2)

    bias = np.zeros((B, 1, T), np.float32)
    bias[0, :, -100:] = -1e9
    bias = jnp.asarray(bias)
    out = jax.jit(lambda q, k, v: flash_attention(q, k, v, bias=bias))(q, k, v)
    ref = dense_attention(q, k, v, bias=bias)
    check("flash fwd bias", out, ref, 2e-2)

    rate, seed = 0.1, 77
    keep = dropout_keep_reference(seed, B, H, T, T, rate)
    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, True, dropout_rate=rate, dropout_seed=seed))(q, k, v)
    ref = dense_attention(q, k, v, causal=True, dropout_keep=keep)
    check("flash fwd dropout", out, ref, 2e-2)
    gf = jax.jit(jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, True, dropout_rate=rate, dropout_seed=seed) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(
        dense_attention(q, k, v, causal=True, dropout_keep=keep) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(gf, gd, "qkv"):
        check(f"flash d{n} dropout", a, b, 3e-2)


def block_sparse_checks():
    from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                    FixedSparsityConfig)
    from deepspeed_tpu.ops.pallas.block_sparse_attention import block_sparse_attention
    from deepspeed_tpu.ops.pallas.flash_attention import dense_attention, DEFAULT_MASK_VALUE
    B, H, T, D = 1, 4, 2048, 64
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32) for _ in range(3))
    for name, cfg in (("fixed", FixedSparsityConfig(num_heads=H, block=128)),
                      ("bigbird", BigBirdSparsityConfig(num_heads=H, block=128))):
        layout = np.asarray(cfg.make_layout(T))
        # the layout is static (LUTs are built at trace time) — close over it
        out = jax.jit(lambda q, k, v, lay=layout, blk=cfg.block: block_sparse_attention(
            q, k, v, lay, block=blk))(q, k, v)
        # dense oracle with the same block mask
        blk = cfg.block
        mask = np.kron(layout, np.ones((blk, blk), np.float32))  # [H, T, T]
        scores = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k)) / math.sqrt(D)
        scores = np.where(mask[None] > 0, scores, DEFAULT_MASK_VALUE)
        probs = jax.nn.softmax(jnp.asarray(scores), axis=-1)
        ref = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        check(f"block-sparse fwd {name}", out, ref, 2e-2)


def gpt2_sparse_check():
    """The sparse kernel wired INTO the GPT-2 model (GPT2Config.sparse_attention)
    on compiled TPU vs per-layer dense attention masked by the same layout."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    from deepspeed_tpu.ops.pallas.block_sparse_attention import \
        dense_blocksparse_attention
    from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig

    V, T, E, NH, BLK = 512, 2048, 128, 4, 128
    sc = BigBirdSparsityConfig(num_heads=NH, block=BLK)
    model = GPT2Model(GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=2,
                                 n_head=NH, compute_dtype=jnp.float32,
                                 sparse_attention=sc))
    params = model.init(jax.random.PRNGKey(3))
    toks = jnp.asarray(np.random.default_rng(4).integers(0, V, (1, T)), jnp.int32)
    got = jax.jit(model.logits)(params, toks)

    layout = np.asarray(sc.make_layout(T))
    oracle = GPT2Model(GPT2Config(vocab_size=V, n_positions=T, n_embd=E, n_layer=2,
                                  n_head=NH, compute_dtype=jnp.float32))

    def masked_attention(self, x, p, dropout_rng=None):
        B_, T_, _ = x.shape
        qkv = jnp.dot(x, p["c_attn_w"].astype(x.dtype)) + p["c_attn_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (a.reshape(B_, T_, NH, E // NH).transpose(0, 2, 1, 3)
                   for a in (q, k, v))
        # the maintained dense-masked oracle (same layout, causal)
        y = dense_blocksparse_attention(q, k, v, layout, BLK, causal=True)
        y = y.transpose(0, 2, 1, 3).reshape(B_, T_, E)
        return jnp.dot(y, p["c_proj_w"].astype(x.dtype)) + p["c_proj_b"].astype(x.dtype)

    oracle._attention = masked_attention.__get__(oracle)
    ref = jax.jit(oracle.logits)(params, toks)
    check("gpt2 sparse_attention logits", got, ref, 2e-2)


def long_context_checks():
    """Chunked long-context flash WITH global-coordinate dropout at T=16384 (past the
    resident kernel's VMEM ceiling) vs the dense oracle — VERDICT r3 #4 acceptance."""
    from deepspeed_tpu.ops.pallas.flash_attention import (
        flash_attention, dense_attention, dropout_keep_reference)
    B, H, T, D = 1, 1, 16384, 64
    rate, seed = 0.1, 321
    rng = np.random.default_rng(2)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
               for _ in range(3))
    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, dropout_rate=rate, dropout_seed=seed))(q, k, v)
    keep = dropout_keep_reference(seed, B, H, T, T, rate)
    ref = jax.jit(lambda q, k, v, keep: dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True, dropout_keep=keep))(q, k, v, keep)
    check("chunked long-context dropout T=16384", out, ref, 3e-2)


def main():
    print(f"backend: {jax.default_backend()}, devices: {jax.devices()}")
    if jax.default_backend() != "tpu":
        print("SKIP: no TPU available (parity smoke targets compiled TPU numerics)")
        return
    flash_checks()
    block_sparse_checks()
    gpt2_sparse_check()
    long_context_checks()
    if FAILURES:
        print(f"\n{len(FAILURES)} parity failures: {FAILURES}")
        sys.exit(1)
    print("\nall TPU parity checks passed")


if __name__ == "__main__":
    main()
