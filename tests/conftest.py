"""Test harness: force an 8-device virtual CPU platform BEFORE jax backends initialize.

This mirrors the reference's multi-process-on-one-host distributed testing strategy
(tests/unit/common.py:14-100's @distributed_test decorator): instead of forking N NCCL
processes, we give JAX 8 virtual CPU devices and run real mesh collectives over them.

Note: this environment's sitecustomize pins ``jax_platforms=axon`` (real TPU tunnel) at
interpreter startup, so the JAX_PLATFORMS env var alone is not enough — we must override
via ``jax.config`` before any backend is touched.
"""

import os

# XLA_FLAGS is read when the CPU backend initializes (lazily) — set it first.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration tests")


# The ROADMAP tier-1 command runs the suite under a hard wall-clock cap. The
# 8-rank interpret-mode ring suites are by far the slowest files (minutes of
# XLA compile + interpret execution each); schedule them last so that if the
# cap truncates the run it cuts into the expensive tail instead of starving
# the hundreds of fast tests collected behind them alphabetically. Stable
# sort: relative order within each group is unchanged.
_HEAVY_FILES = ("test_ring_attention.py", "test_ring_zigzag.py")


def pytest_collection_modifyitems(config, items):
    items.sort(key=lambda item: os.path.basename(str(item.fspath)) in _HEAVY_FILES)
