"""Which remat policy avoids replaying the flash fwd kernel in backward?

Compiles value_and_grad of a 2-layer rematted GPT-2 on the TPU and counts
pallas custom-calls in the HLO, classified by kernel (fwd vs bwd_dq vs
bwd_dkv). A policy that saves the kernel's (out, lse) should show ONE fwd
kernel per layer; dots shows TWO (one fwd + one backward replay).

Usage: python tests/perf/remat_flash_probe.py [policy ...]
"""

import re
import sys

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model


def count_kernels(policy):
    cfg = GPT2Config(vocab_size=2048, n_positions=512, n_embd=256, n_layer=2,
                     n_head=4, remat=True, remat_policy=policy,
                     use_flash_attention=True)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tok = jnp.zeros((2, 512), jnp.int32)
    lab = jnp.zeros((2, 512), jnp.int32)

    f = jax.jit(jax.value_and_grad(lambda p: model.apply(p, tok, lab)))
    txt = f.lower(params).compile().as_text()
    calls = [c for c in re.findall(r'.*custom-call[^\n]*', txt)
             if "tpu_custom_call" in c]
    # classify by output signature: fwd = (bf16 out, f32 lse) pair; dkv = (bf16,
    # bf16) pair; dq = single bf16. A fwd call inside a rematted_computation is
    # the backward-pass REPLAY the policy is supposed to eliminate.
    def sig(c):
        m = re.search(r"= (\(.*?\)|\S+) custom-call", c)
        return tuple(re.findall(r"(bf16|f32)\[", m.group(1))) if m else ()
    fwd = [c for c in calls if sig(c) == ("bf16", "f32")]
    dkv = [c for c in calls if sig(c) == ("bf16", "bf16")]
    dq = [c for c in calls if sig(c) == ("bf16",)]
    replay = [c for c in fwd if "remat" in c]
    return {"fwd_total": len(fwd), "fwd_replayed": len(replay),
            "bwd_dq": len(dq), "bwd_dkv": len(dkv),
            "unclassified": len(calls) - len(fwd) - len(dkv) - len(dq)}


if __name__ == "__main__":
    policies = sys.argv[1:] or ["dots", "attn", "dots+attn"]
    print("devices:", jax.devices())
    for p in policies:
        print(p, "->", count_kernels(p))
