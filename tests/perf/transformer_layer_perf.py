"""BERT-large encoder layer fwd+bwd on the real TPU (slope-timed; see devtime.py).

The reference's headline: 64 TFLOPS (seq 128) / 53 TFLOPS (seq 512) for its fused
fp16 CUDA kernel on V100 (docs/_tutorials/bert-pretraining.md:387). Mask + train-mode
dropout active (the flash kernel's in-kernel mask+dropout path).

    python tests/perf/transformer_layer_perf.py
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope_stats  # noqa: E402
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,  # noqa: E402
                                           DeepSpeedTransformerLayer)


def layer_flops(batch, seq, hidden, inter, heads):
    mm = 2 * batch * seq * hidden * (3 * hidden + hidden) + 2 * batch * seq * (
        hidden * inter + inter * hidden)
    attn = 4 * batch * heads * seq * seq * (hidden // heads)
    return 3.5 * (mm + attn)  # fwd + ~2.5x bwd (flash recompute included)


def main():
    H, I, NH = 1024, 4096, 16  # BERT-large
    rng = np.random.default_rng(0)
    for seq, batch in ((128, 64), (512, 16)):
        cfg = DeepSpeedTransformerConfig(
            batch_size=batch, max_seq_length=seq, hidden_size=H, intermediate_size=I,
            heads=NH, attn_dropout_ratio=0.1, hidden_dropout_ratio=0.1,
            num_hidden_layers=24, fp16=False, pre_layer_norm=True)
        layer = DeepSpeedTransformerLayer(cfg)
        params = layer.init(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.normal(size=(batch, seq, H)), jnp.bfloat16)
        mask = jnp.zeros((batch, 1, 1, seq), jnp.float32)
        key = jax.random.PRNGKey(1)

        def loss(x, params):
            out = layer.apply(params, x, attention_mask=mask, rng=key,
                              deterministic=False)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        g = lambda x, params: jax.grad(loss, argnums=(0, 1))(x, params)[0]
        dt, sp, sc = timeit_slope_stats(g, x, params, n1=10, n2=50)
        fl = layer_flops(batch, seq, H, I, NH)
        print(f"seq={seq} batch={batch}: {dt*1e3:.3f} ms ±{sp:.1%} (x{sc})  "
              f"{fl/dt/1e12:.1f} TF/s "
              f"(reference V100 claim: {64 if seq == 128 else 53} TFLOPS)")


if __name__ == "__main__":
    main()
