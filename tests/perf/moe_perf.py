"""MoE layer vs dense MLP of the same per-token FLOPs on the real TPU
(slope-timed): what the switch routing + grouped dispatch costs over the pure
expert compute. python tests/perf/moe_perf.py"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope_stats  # noqa: E402
from deepspeed_tpu.parallel.moe import MoELayer  # noqa: E402


def main():
    H, F, E = 1024, 4096, 8
    B, T = 8, 1024
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, T, H)), jnp.bfloat16)

    layer = MoELayer(H, F, E, capacity_factor=1.25, group_size=T)
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16),
                                    layer.init(jax.random.PRNGKey(0)))

    w1 = jnp.asarray(rng.normal(size=(H, F)) * 0.02, jnp.bfloat16)
    w2 = jnp.asarray(rng.normal(size=(F, H)) * 0.02, jnp.bfloat16)

    def dense_mlp(x):
        h = jax.nn.gelu(jnp.einsum("bth,hf->btf", x, w1,
                                   preferred_element_type=jnp.float32).astype(x.dtype))
        return jnp.einsum("btf,fh->bth", h, w2, preferred_element_type=jnp.float32)

    def moe(x):
        y, aux = layer.apply(params, x)
        return y.astype(jnp.float32) + aux

    dt_d, sp_d, _ = timeit_slope_stats(dense_mlp, x, n1=20, n2=100)
    dt_m, sp_m, _ = timeit_slope_stats(moe, x, n1=20, n2=100)
    n_tok = B * T
    flops = 4.0 * n_tok * H * F  # per-token 2 matmuls (same active FLOPs both paths)
    print(f"dense MLP   (H={H}, F={F}):        {dt_d*1e3:7.3f} ms ±{sp_d:.1%} "
          f"-> {flops/dt_d/1e12:.0f} TF/s")
    print(f"switch MoE  (E={E}, cf=1.25, g={T}): {dt_m*1e3:7.3f} ms ±{sp_m:.1%} "
          f"-> {flops/dt_m/1e12:.0f} TF/s active")
    print(f"routing+dispatch overhead: {dt_m/dt_d:.2f}x the dense MLP at equal "
          f"per-token FLOPs ({E}x the parameters)")


if __name__ == "__main__":
    main()
