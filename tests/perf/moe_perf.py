"""MoE layer vs dense MLP of the same per-token FLOPs on the real TPU
(slope-timed): what the switch routing + grouped dispatch costs over the pure
expert compute. python tests/perf/moe_perf.py"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope_stats  # noqa: E402
from deepspeed_tpu.parallel.moe import MoELayer  # noqa: E402


def main():
    H, F, E = 1024, 4096, 8
    B, T = 8, 1024
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, T, H)), jnp.bfloat16)

    layer_sc = MoELayer(H, F, E, capacity_factor=1.25, group_size=T,
                        dispatch="scatter")
    layer_ei = MoELayer(H, F, E, capacity_factor=1.25, group_size=T,
                        dispatch="einsum")  # the default
    params = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16),
                                    layer_sc.init(jax.random.PRNGKey(0)))

    w1 = jnp.asarray(rng.normal(size=(H, F)) * 0.02, jnp.bfloat16)
    w2 = jnp.asarray(rng.normal(size=(F, H)) * 0.02, jnp.bfloat16)

    def dense_mlp(x):
        h = jax.nn.gelu(jnp.einsum("bth,hf->btf", x, w1,
                                   preferred_element_type=jnp.float32).astype(x.dtype))
        return jnp.einsum("btf,fh->bth", h, w2, preferred_element_type=jnp.float32)

    def moe_scatter(x):
        y, aux = layer_sc.apply(params, x)
        return y.astype(jnp.float32) + aux

    def moe_einsum(x):
        y, aux = layer_ei.apply(params, x)
        return y.astype(jnp.float32) + aux

    dt_d, sp_d, _ = timeit_slope_stats(dense_mlp, x, n1=20, n2=100)
    dt_s, sp_s, _ = timeit_slope_stats(moe_scatter, x, n1=20, n2=100)
    dt_e, sp_e, _ = timeit_slope_stats(moe_einsum, x, n1=20, n2=100)
    n_tok = B * T
    flops = 4.0 * n_tok * H * F  # per-token 2 matmuls (same active FLOPs both paths)
    print(f"dense MLP   (H={H}, F={F}):        {dt_d*1e3:7.3f} ms ±{sp_d:.1%} "
          f"-> {flops/dt_d/1e12:.0f} TF/s")
    print(f"switch MoE einsum  (E={E}, cf=1.25, g={T}): {dt_e*1e3:7.3f} ms ±{sp_e:.1%} "
          f"-> {flops/dt_e/1e12:.0f} TF/s active")
    print(f"switch MoE scatter (E={E}, cf=1.25, g={T}): {dt_s*1e3:7.3f} ms ±{sp_s:.1%} "
          f"-> {flops/dt_s/1e12:.0f} TF/s active")
    print(f"routing+dispatch overhead: einsum {dt_e/dt_d:.2f}x / scatter "
          f"{dt_s/dt_d:.2f}x the dense MLP at equal per-token FLOPs "
          f"({E}x the parameters); einsum:scatter time ratio {dt_e/dt_s:.2f} "
          f"(einsum is the default — it measures faster on TPU)")


if __name__ == "__main__":
    main()
