"""Peak-RSS probe for the pipeline flush/segment schedules (8-virtual-device CPU).

Reproduces the PERF.md "Pipeline memory at M >> S" row and extends it to the
streamed single-fill schedule: each mode runs `jax.grad` of the GPT2Pipe loss at
M = 16S in a fresh subprocess and reports `ru_maxrss`.

Usage: python tests/perf/pipeline_mem_probe.py            # all modes
       python tests/perf/pipeline_mem_probe.py --one MODE # child (internal)
"""

import subprocess
import sys

MODES = ("single", "legacy", "streamed")


def child(mode):
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import resource

    from deepspeed_tpu.models.gpt2 import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import GPT2Pipe
    from deepspeed_tpu.parallel.mesh import build_mesh
    import deepspeed_tpu.parallel.pipeline_spmd as ps

    S, M = 2, 32
    cfg = GPT2Config(vocab_size=512, n_positions=512, n_embd=128, n_layer=2,
                     n_head=4, compute_dtype=jnp.bfloat16)
    mesh = build_mesh(pipe=S, model=1)
    pipe = GPT2Pipe(cfg, num_stages=S)
    params = pipe.init(jax.random.PRNGKey(0))
    placed = jax.device_put(params, pipe.param_shardings(mesh, params))
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(M, 16, 512)).astype(np.int32))
    labels = jnp.asarray(np.roll(np.asarray(toks), -1, axis=2))

    cap = 0 if mode == "single" else None
    stream = mode == "streamed"

    def loss(p):
        return pipe.loss(p, toks, labels, mesh=mesh,
                         max_microbatches_per_flush=cap, stream_segments=stream)

    g = jax.jit(jax.grad(loss))(placed)
    jax.block_until_ready(g)
    peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    print(f"RESULT {mode} peak_rss_mb={peak_mb:.0f}")


if __name__ == "__main__":
    if "--one" in sys.argv:
        child(sys.argv[sys.argv.index("--one") + 1])
    else:
        for mode in MODES:
            r = subprocess.run([sys.executable, __file__, "--one", mode],
                               capture_output=True, text=True, timeout=1200)
            for line in r.stdout.splitlines():
                if line.startswith("RESULT"):
                    print(line)
                    break
            else:
                print(f"{mode} FAILED:", r.stderr.splitlines()[-3:])
