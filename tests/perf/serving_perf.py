"""Continuous-batching serving measurements on the real TPU.

Replays seeded mixed greedy/beam traces (deepspeed_tpu.serve.sim.synth_trace)
through the InferenceEngine at GPT-2 420M and 1.5B bf16, sweeping slot count
and the XLA-gather vs Pallas paged-decode attention path. Reports per config:
decode tok/s, goodput tok/s, mean TTFT, mean slot occupancy, preemptions, and
the compile-watchdog recompile count (must be 0 after warmup — the same gate
``ds-tpu serve-sim`` enforces on the CPU mesh).

Relay-safe timing: the engine loop fetches every logits row to the host each
iteration (sampling is host-side), so every step is naturally fenced; walls
are seconds, far above the ~107 ms fence noise.

    python tests/perf/serving_perf.py [--small-only] [--requests N]

Deliberately NOT named test_*.py: this is a minutes-long benchmark driver,
excluded from tier-1 collection (tests/unit/test_tier1_collection.py pins
that).
"""

import argparse
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.serve.engine import InferenceEngine
from deepspeed_tpu.serve.sim import synth_trace
from deepspeed_tpu.utils.monitor import SummaryMonitor
from deepspeed_tpu.utils.telemetry import TelemetrySession

ML = 1024            # serving context budget (tokens)

MODELS = {
    "420M": dict(vocab_size=50304, n_positions=ML, n_embd=1024,
                 n_layer=24, n_head=16, use_flash_attention=True),
    "1.5B": dict(vocab_size=50304, n_positions=ML, n_embd=1600,
                 n_layer=48, n_head=25, use_flash_attention=True),
}


def _require_tpu():
    if jax.devices()[0].platform == "cpu":
        print("serving_perf: needs a real TPU (use `ds-tpu serve-sim` for "
              "the CPU-mesh correctness replay)", file=sys.stderr)
        sys.exit(2)


def bench_config(name, cfg_kwargs, *, num_slots, use_pallas, n_requests,
                 seed=11):
    cfg = GPT2Config(**cfg_kwargs)
    model = GPT2Model(cfg)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16) if p.ndim >= 2 else p,
        model.init(jax.random.PRNGKey(0)))
    session = TelemetrySession(monitor=SummaryMonitor(enabled=False))
    eng = InferenceEngine(model, params, num_slots=num_slots, block_size=16,
                          num_blocks=num_slots * (ML // 16) // 2 + 1,
                          max_model_len=ML, prefill_chunk=128,
                          use_pallas=use_pallas, telemetry=session)
    reqs = synth_trace(n_requests, vocab_size=cfg.vocab_size,
                       max_model_len=ML, seed=seed)
    t0 = time.time()
    outs, logs = eng.run(reqs)
    wall = max(time.time() - t0, 1e-9)
    fin = [o for o in outs if o.status == "finished"]
    new_tokens = sum(len(o.tokens) for o in fin)
    occ = float(np.mean([len(log["decode"]) / num_slots for log in logs]))
    recompiles = sum(session.watchdog.recompiles(n)
                     for n in session.watchdog.records
                     if n.startswith("serve:"))
    path = "pallas" if use_pallas else "xla-gather"
    print(f"{name:5s} slots={num_slots:3d} {path:10s} "
          f"tok/s={eng._tokens_sampled / wall:8.1f} "
          f"goodput={new_tokens / wall:8.1f} "
          f"ttft_ms={np.mean([o.ttft_ms for o in fin]):8.1f} "
          f"occ={occ:.3f} preempt={sum(o.preemptions for o in fin):3d} "
          f"recompiles={recompiles}", flush=True)
    assert recompiles == 0, "serving decode program recompiled after warmup"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small-only", action="store_true")
    ap.add_argument("--requests", type=int, default=32)
    args = ap.parse_args()
    _require_tpu()
    names = ["420M"] if args.small_only else ["420M", "1.5B"]
    for name in names:
        for num_slots in (8, 32):
            for use_pallas in (False, True):
                bench_config(name, MODELS[name], num_slots=num_slots,
                             use_pallas=use_pallas,
                             n_requests=args.requests)


if __name__ == "__main__":
    main()
