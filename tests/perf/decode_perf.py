"""KV-cache decode + prefill measurements on the real TPU (VERDICT r4 #3).

Times `GPT2Model.generate` (greedy) and `beam_search` (beam-4) for GPT-2 420M and
1.5B at batch 1 and 8: decode tokens/s (isolated from prefill by differencing a
long and a 1-token generation) and prefill TFLOP/s over a 1024-token prompt.

Relay-safe timing: every measurement fences with a device_get of the output
tokens (block_until_ready does not fence over the axon relay — see PERF.md);
decode/prefill walls are 100s of ms to seconds, far above the ~107 ms fence noise,
and min-of-reps is reported.

    python tests/perf/decode_perf.py [--small-only]
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

T0 = 1024        # prompt length
NEW = 128        # generated tokens for the decode-rate measurement
REPS = 3

MODELS = {
    "420M": dict(vocab_size=50304, n_positions=T0 + NEW + 8, n_embd=1024,
                 n_layer=24, n_head=16, use_flash_attention=True),
    "1.5B": dict(vocab_size=50304, n_positions=T0 + NEW + 8, n_embd=1600,
                 n_layer=48, n_head=25, use_flash_attention=True),
}


def fence(x):
    # device_get (not block_until_ready) fences over the relay; handle the
    # (sequences, scores) tuple beam_search returns
    return jax.tree_util.tree_leaves(jax.device_get(x))[0]


def time_call(fn, reps=REPS):
    fence(fn())  # compile + warm
    fence(fn())  # donation/layout recompile settles
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        fence(fn())
        best = min(best, time.time() - t0)
    return best


def bench_model(name, cfg_kwargs, batches=(1, 8), do_beam=True):
    cfg = GPT2Config(**cfg_kwargs)
    model = GPT2Model(cfg)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16) if p.ndim >= 2 else p,
        model.init(jax.random.PRNGKey(0)))
    n_params = model.param_count(params)
    rows = []
    for B in batches:
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, size=(B, T0)),
            jnp.int32)

        t1 = time_call(lambda: model.generate(params, prompt, 1))
        t_long = time_call(lambda: model.generate(params, prompt, NEW))
        greedy_tps = (NEW - 1) * B / max(t_long - t1, 1e-9)
        # prefill: fwd-only flops over the prompt, ~2*N per token (+ attention)
        prefill_tf = 2.0 * n_params * B * T0 / t1 / 1e12
        row = {"model": name, "batch": B, "prefill_s": round(t1, 3),
               "prefill_tf_s": round(prefill_tf, 1),
               "greedy_tok_s": round(greedy_tps, 1)}
        if do_beam:
            tb1 = time_call(lambda: model.beam_search(params, prompt, 1, num_beams=4))
            tbl = time_call(lambda: model.beam_search(params, prompt, NEW, num_beams=4))
            row["beam4_tok_s"] = round((NEW - 1) * B / max(tbl - tb1, 1e-9), 1)
        rows.append(row)
        print(row, flush=True)
    del params
    return rows


def main():
    print("devices:", jax.devices())
    names = ["420M"] if "--small-only" in sys.argv else ["420M", "1.5B"]
    everything = "--all" in sys.argv
    for name in names:
        for B in (1, 8):
            # two configs reproducibly crash THIS rig's relay TPU worker (compile
            # succeeds; the worker dies mid-run — see PERF.md decode table):
            # beam-4 at batch 8, and the 1.5B batch-8 long decode. Skip them by
            # default so the documented repro command completes; --all runs them.
            beam = True
            if not everything:
                if name == "1.5B" and B == 8:
                    print(f"SKIP {name} batch={B} (crashes the relay worker; "
                          "run with --all to attempt)", flush=True)
                    continue
                beam = B != 8
            bench_model(name, MODELS[name], batches=(B,), do_beam=beam)


if __name__ == "__main__":
    main()
