"""Slope-based kernel timing for the axon relay.

A single device_get fence over the relay costs ~107 ms (measured 2026-07-30) and
per-dispatch overhead is ~10 ms, so host-loop timings of ms-scale kernels are pure
noise. This harness iterates the kernel ON DEVICE inside one jit (serial dependency
defeats CSE/overlap) at two iteration counts and reports the SLOPE — the fence and
dispatch costs cancel exactly:

    t = (T(n2) - T(n1)) / (n2 - n1)

Negative results mean fence variance still exceeds the compute delta: raise n1/n2.
``timeit_slope`` returns the best (min) slope; ``timeit_slope_stats`` returns a
reproducible median with its spread, escalating the on-device iteration counts
until the spread pins below a target — sub-ms kernels at small n sit at the
fence-variance noise floor, where a single best-of-reps can wander 2x between runs.
"""

import time

import jax
import jax.numpy as jnp


def _make_loop(fn, inner):
    @jax.jit
    def many(*a):
        def body(_, s):
            # Serial dependency XLA cannot fold away: the carry enters the
            # kernel input scaled by a nonzero constant (a literal ``* 0``
            # would constant-fold, making the body loop-invariant and
            # hoistable, flattening the slope). The dtype's smallest NORMAL
            # value is nonzero in every float dtype (a fixed 1e-30 would
            # itself round to literal 0.0 in fp16 and restore the fold) and
            # perturbs inputs by less than one ulp.
            tiny = jnp.asarray(jnp.finfo(a[0].dtype).tiny, a[0].dtype)
            out = fn(a[0] + s.astype(a[0].dtype) * tiny, *a[1:])
            return jnp.sum(out.astype(jnp.float32)) * 1e-30
        return jax.lax.fori_loop(0, inner, body, jnp.zeros((), jnp.float32))
    return many


def _slopes(fn, args, n1, n2, reps):
    """Per-rep slope estimates (seconds/call) at the given iteration counts."""
    f1, f2 = _make_loop(fn, n1), _make_loop(fn, n2)
    for f in (f1, f2):
        f(*args)
        float(jax.device_get(f(*args)))
    out = []
    for _ in range(reps):
        t0 = time.time()
        float(jax.device_get(f1(*args)))
        ta = time.time() - t0
        t0 = time.time()
        float(jax.device_get(f2(*args)))
        tb = time.time() - t0
        out.append((tb - ta) / (n2 - n1))
    return out


def timeit_slope(fn, *args, n1=10, n2=50, reps=3):
    """Per-call seconds of ``fn(*args)`` (first arg must be a float array)."""
    return min(_slopes(fn, args, n1, n2, reps))


def timeit_slope_stats(fn, *args, n1=10, n2=50, reps=5, target_spread=0.10,
                       max_scale=8):
    """Reproducible per-call seconds: (median, spread, n_scale).

    Runs ``reps`` slope estimates and, while their spread ((max-min)/median)
    exceeds ``target_spread`` or the median is non-positive, DOUBLES the on-device
    iteration counts (more compute per fence → the fence variance amortizes away).
    Each escalation costs two fresh jit compiles; ``max_scale`` bounds it.
    """
    scale = 1
    while True:
        s = sorted(_slopes(fn, args, n1 * scale, n2 * scale, reps))
        med = s[len(s) // 2]
        spread = (s[-1] - s[0]) / med if med > 0 else float("inf")
        if (med > 0 and spread <= target_spread) or scale >= max_scale:
            return med, spread, scale
        scale *= 2
