"""Flash-attention block-size sweep on the real TPU (slope-timed; see devtime.py —
host-loop timings over the axon relay are fence-noise).

    python tests/perf/flash_sweep.py [--bwd]
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope  # noqa: E402
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402


def main():
    do_bwd = "--bwd" in sys.argv
    B, H, D = 1, 16, 64
    rng = np.random.default_rng(0)
    for T, causal in ((4096, False), (4096, True), (8192, False), (8192, True)):
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        flops = 4.0 * B * H * T * T * D * (0.5 if causal else 1.0)
        for bq, bk in ((None, None), (256, 512), (512, 1024), (1024, 1024)):
            label = "auto" if bq is None else f"bq={bq} bk={bk}"
            try:
                dt = timeit_slope(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=causal, block_q=bq, block_k=bk), q, k, v,
                    n1=20, n2=100)
                print(f"T={T} causal={int(causal)} {label}: {dt*1e3:7.3f} ms "
                      f"{flops/dt/1e12:6.1f} TF/s")
                if do_bwd:
                    g = lambda q, k, v, bq=bq, bk=bk: jax.grad(
                        lambda q: jnp.sum(flash_attention(
                            q, k, v, causal=causal, block_q=bq,
                            block_k=bk).astype(jnp.float32)))(q)
                    dt = timeit_slope(g, q, k, v, n1=5, n2=30)
                    print(f"T={T} causal={int(causal)} {label} +bwd: {dt*1e3:7.3f} ms "
                          f"{3.5*flops/dt/1e12:6.1f} TF/s")
            except Exception as e:
                print(f"T={T} causal={int(causal)} {label}: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
