"""Flash-attention block-size sweep on the real TPU (VERDICT r3 kernel roofline work).

Times the fwd kernel (and optionally fwd+bwd) across (block_q, block_k) at long seq.
Fence via device_get (axon relay: block_until_ready does not fence). Run:

    python tests/perf/flash_sweep.py [--bwd]
"""

import itertools
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402


def time_fn(fn, *args, iters=10):
    fn(*args)  # compile
    float(jax.device_get(jnp.sum(fn(*args))))  # warm + fence
    best = float("inf")
    for _ in range(2):
        t0 = time.time()
        for _ in range(iters):
            out = fn(*args)
        float(jax.device_get(jnp.sum(out)))
        best = min(best, (time.time() - t0) / iters)
    return best


def main():
    do_bwd = "--bwd" in sys.argv
    B, H, D = 1, 16, 64
    rng = np.random.default_rng(0)
    for T, causal in ((4096, True), (4096, False), (8192, False)):
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        flops = 4.0 * B * H * T * T * D * (0.5 if causal else 1.0)
        for bq, bk in itertools.product((256, 512), (512, 1024, 2048)):
            if bq > T or bk > T:
                continue
            try:
                f = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=causal, block_q=bq, block_k=bk))
                dt = time_fn(f, q, k, v)
                tag = f"T={T} causal={int(causal)} bq={bq} bk={bk}"
                print(f"{tag}: {dt*1e3:.2f} ms  {flops/dt/1e12:.1f} TF/s")
                if do_bwd:
                    g = jax.jit(jax.grad(lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                        flash_attention(q, k, v, causal=causal, block_q=bq,
                                        block_k=bk).astype(jnp.float32))))
                    dt = time_fn(g, q, k, v)
                    print(f"{tag} +bwd: {dt*1e3:.2f} ms  {3.5*flops/dt/1e12:.1f} TF/s")
            except Exception as e:
                print(f"T={T} bq={bq} bk={bk}: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
