"""Model-level long-sequence bench (VERDICT r4 #7): sparse vs dense GPT-2 at
T=8192 END TO END through DeepSpeedEngine — tokens/s and MFU, the model-level
counterpart of the 4.58x kernel number (the reference's long-seq claims are
model-level: "10x longer sequences, up to 6x faster", reference README.md:17,35).

Config: GPT-2 (12L, 1024E, 16H) at T=8192, batch 1, ZeRO-2 engine, bf16.
Sparse = BigBird-family sliding-window band at block 256 (the round-4 gap
decomposition's best TPU-shaped layout, PERF.md block-sparse section); dense =
the flash kernel's chunked long-context path.

    python tests/perf/long_seq_model_perf.py
"""

import time

import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.ops.sparse_attention.sparsity_config import VariableSparsityConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel.mesh import build_mesh

T, B, LAYERS, EMBD, HEADS = 8192, 1, 12, 1024, 16
PEAK_TFLOPS = 197.0  # v5e bf16


def fence(x):
    return np.asarray(jax.device_get(x))


def run_engine(sparse):
    common = dict(vocab_size=50304, n_positions=T, n_embd=EMBD, n_layer=LAYERS,
                  n_head=HEADS, remat=True, remat_policy="dots", loss_chunk=512)
    if sparse:
        # sliding-window band, block 256: the layout the round-4 kernel probe
        # pinned at 4.58x over dense flash at T=8192 (~9% density)
        sc = VariableSparsityConfig(num_heads=HEADS, block=256,
                                    num_random_blocks=0,
                                    local_window_blocks=[3],
                                    global_block_indices=[0],
                                    attention="unidirectional")
        cfg = GPT2Config(sparse_attention=sc, **common)
    else:
        cfg = GPT2Config(use_flash_attention=True, **common)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n_params = model.param_count(params)
    engine = DeepSpeedEngine(
        model=model, model_parameters=params, mesh=build_mesh(model=1, pipe=1),
        config_params={"train_batch_size": B, "steps_per_print": 1000,
                       "bf16": {"enabled": True},
                       "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                       "zero_optimization": {"stage": 2}})
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(B, T)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1)

    def step():
        loss = engine(tokens, labels)
        engine.backward(loss)
        engine.step()
        return loss

    step()
    fence(step())  # donated-layout recompile settles
    # median-of-3 windows + recorded spread (same policy as the bench.py
    # headline rows: a best-of draw biases the long-seq claim high on the
    # shared tunnel chip)
    steps, dts = 3, []
    for _ in range(3):
        t0 = time.time()
        for _ in range(steps):
            loss = step()
        fence(loss)
        dts.append(time.time() - t0)
    dts.sort()
    dt = dts[1]
    spread = (dts[-1] - dts[0]) / dt
    tps = B * T * steps / dt
    mfu = tps * 6.0 * n_params / 1e12 / PEAK_TFLOPS
    name = "sparse-band256" if sparse else "dense-flash"
    print(f"{name}: {tps:,.1f} tok/s  param-MFU {mfu:.4f}  "
          f"({dt/steps:.3f} s/step median-of-3, spread {spread:.1%}, "
          f"{n_params/1e6:.0f}M params)", flush=True)
    del engine, params
    import gc
    gc.collect()
    return tps, mfu


def main():
    print("devices:", jax.devices())
    d_tps, d_mfu = run_engine(sparse=False)
    s_tps, s_mfu = run_engine(sparse=True)
    print(f"model-level speedup sparse/dense at T={T}: {s_tps / d_tps:.2f}x")


if __name__ == "__main__":
    main()
