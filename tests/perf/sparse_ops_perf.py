"""Standalone sparse MatMul (sdd/dsd/dds) + Softmax ops vs dense XLA and the fused
Pallas kernel on the real TPU (slope-timed; VERDICT r3 #6).

These ops are the API-parity analogs of the reference's Triton matmul/softmax
(ops/sparse_attention/matmul.py:595-729, softmax.py:207-292). Their dsd/dds and
segmented-softmax paths use `.at[...].add` scatter-adds, which on TPU can be far
off the fused kernel — this runner measures exactly how far, so the docs can say
whether a hot path may be built on them.

    python tests/perf/sparse_ops_perf.py
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope_stats  # noqa: E402
from deepspeed_tpu.ops.pallas.block_sparse_attention import block_sparse_attention  # noqa: E402
from deepspeed_tpu.ops.sparse_attention.matmul import MatMul  # noqa: E402
from deepspeed_tpu.ops.sparse_attention.softmax import Softmax  # noqa: E402
from deepspeed_tpu.ops.sparse_attention.sparsity_config import BigBirdSparsityConfig  # noqa: E402


def main():
    B, H, D, BLOCK = 1, 16, 64, 128
    rng = np.random.default_rng(0)
    for T in (4096, 8192):
        cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK)
        layout = np.asarray(cfg.make_layout(T))
        density = float(layout.mean())
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        n1, n2 = (20, 100) if T <= 4096 else (10, 50)
        print(f"== T={T} density={density:.3f} (BigBird, block {BLOCK}) ==")

        # composed op-by-op attention: sdd scores -> sparse softmax -> dsd @ v
        sdd = MatMul(layout, BLOCK, "sdd", trans_b=True)
        dsd = MatMul(layout, BLOCK, "dsd")
        smax = Softmax(layout, BLOCK)
        scale = 1.0 / np.sqrt(D)

        def composed(q, k, v):
            s = sdd(q, k)
            p = smax(s, scale=scale)
            return dsd(p.astype(q.dtype), v)

        def dense(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) * scale
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v,
                              preferred_element_type=jnp.float32).astype(q.dtype)

        def fused(q, k, v):
            return block_sparse_attention(q, k, v, layout, BLOCK)

        for name, fn, (a1, a2) in (("composed sdd+softmax+dsd", composed, (n1, n2)),
                                   ("dense XLA attention", dense, (n1, n2)),
                                   ("fused pallas kernel", fused, (n1, n2))):
            dt, sp, sc = timeit_slope_stats(fn, q, k, v, n1=a1, n2=a2)
            print(f"  {name:28s}: {dt*1e3:8.3f} ms ±{sp:.1%} (x{sc})")

        # individual ops (their own slope rows, for the docs table)
        s_vals = sdd(q, k)
        dt, sp, _ = timeit_slope_stats(lambda a, b: sdd(a, b), q, k, n1=n1, n2=n2)
        print(f"  {'MatMul sdd (q@k^T)':28s}: {dt*1e3:8.3f} ms ±{sp:.1%}")
        dt, sp, _ = timeit_slope_stats(lambda s: smax(s, scale=scale), s_vals,
                                       n1=n1, n2=n2)
        print(f"  {'Softmax (segmented)':28s}: {dt*1e3:8.3f} ms ±{sp:.1%}")
        p_vals = smax(s_vals, scale=scale).astype(q.dtype)
        dt, sp, _ = timeit_slope_stats(lambda p, b: dsd(p, b), p_vals, v, n1=n1, n2=n2)
        print(f"  {'MatMul dsd (p@v)':28s}: {dt*1e3:8.3f} ms ±{sp:.1%}")
        dds = MatMul(layout, BLOCK, "dds", trans_a=True)
        dt, sp, _ = timeit_slope_stats(lambda a, b: dds(a, b), q, s_vals, n1=n1, n2=n2)
        print(f"  {'MatMul dds (q^T@s)':28s}: {dt*1e3:8.3f} ms ±{sp:.1%}")


if __name__ == "__main__":
    main()
