"""Single-chip long-context flash (chunked tile path) with and without in-kernel
attention dropout, slope-timed (PERF.md long-context rows; VERDICT r3 #4 asked for
the dropout-on re-measurement once global-coordinate dropout landed).

    python tests/perf/long_context_perf.py
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope_stats  # noqa: E402
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402


def tf(t, T, B, H, D, causal, bwd):
    flops = 4.0 * B * H * T * T * D * (0.5 if causal else 1.0) * (2.5 if bwd else 1.0)
    return flops / t / 1e12


def main():
    B, H, D = 1, 8, 64
    rng = np.random.default_rng(0)
    for T, causal in ((16384, False), (16384, True), (32768, True)):
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        for rate in (0.0, 0.1):
            kw = {} if rate == 0 else {"dropout_rate": rate, "dropout_seed": 7}

            def fwd_bwd(q, k, v):
                return jax.grad(lambda q: jnp.sum(flash_attention(
                    q, k, v, causal=causal, **kw).astype(jnp.float32)))(q)

            dt, sp, sc = timeit_slope_stats(fwd_bwd, q, k, v, n1=3, n2=12, reps=3,
                                            max_scale=4)
            print(f"T={T} causal={causal} dropout={rate}: {dt*1e3:7.2f} ms ±{sp:.1%} "
                  f"(x{sc}) fwd+bwd -> {tf(dt, T, B, H, D, causal, True):.0f} TF/s")


if __name__ == "__main__":
    main()
