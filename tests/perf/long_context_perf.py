"""Single-chip long-context flash (chunked tile path) with and without in-kernel
attention dropout, slope-timed (PERF.md long-context rows; VERDICT r3 #4 asked for
the dropout-on re-measurement once global-coordinate dropout landed), plus the
masked-vs-zigzag causal ring sweep at T=8192 over an 8-device mesh (PR 2
tentpole: the zigzag schedule removes the masked ring's ~2x dead-compute tax).

    python tests/perf/long_context_perf.py             # chunked flash sweep (1 chip)
    python tests/perf/long_context_perf.py --ring      # ring sweep (needs 8 devices)
    python tests/perf/long_context_perf.py --ring-cpu  # ring sweep on 8 virtual CPU devices
"""

import os
import sys

# --ring-cpu must claim the virtual CPU platform BEFORE jax initializes (this
# rig's sitecustomize pins the axon relay TPU otherwise — see tests/conftest.py)
if "--ring-cpu" in sys.argv:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np

import jax
import jax.numpy as jnp

if "--ring-cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope_stats  # noqa: E402
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402


def tf(t, T, B, H, D, causal, bwd):
    flops = 4.0 * B * H * T * T * D * (0.5 if causal else 1.0) * (2.5 if bwd else 1.0)
    return flops / t / 1e12


def ring_sweep(T=8192, B=1, H=2, D=64, reps=3):
    """Causal ring attention fwd+bwd, masked vs zigzag schedule, same mesh and
    shapes — the PR 2 tentpole's headline measurement. Times the shard_map'ped
    LOCAL ring (the sharded wrapper's one-off layout gather is not part of the
    per-step cost) and prints the per-rotation work-balance table alongside, so
    the measured ratio can be read against the analytic 31/17 at n=8."""
    import functools
    import time

    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.mesh import build_mesh, shard_map
    from deepspeed_tpu.parallel.ring_attention import (ring_attention,
                                                       ring_work_schedule)

    n = 8
    assert len(jax.devices()) >= n, (
        f"ring sweep needs {n} devices (got {len(jax.devices())}); on a "
        f"single-chip rig run with --ring-cpu for the 8-virtual-device mesh")
    mesh = build_mesh(data=n, model=1, pipe=1)
    on_tpu = jax.devices()[0].platform == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)), dtype) for _ in range(3))
    spec = P(None, None, "data", None)
    print(f"ring sweep: T={T} B={B} H={H} D={D} n={n} "
          f"({'tpu' if on_tpu else 'cpu interpret'})", flush=True)

    results = {}
    for schedule in ("masked", "zigzag"):
        local = shard_map(
            functools.partial(ring_attention, axis_name="data", causal=True,
                              interpret=not on_tpu, schedule=schedule),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        step = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(local(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))
        np.asarray(jax.device_get(step(q, k, v)[0]))  # compile + warm
        dts = []
        for _ in range(reps):
            t0 = time.time()
            np.asarray(jax.device_get(step(q, k, v)[0]))
            dts.append(time.time() - t0)
        dts.sort()
        dt, spread = dts[len(dts) // 2], (dts[-1] - dts[0]) / dts[len(dts) // 2]
        results[schedule] = dt
        print(f"  {schedule:>6}: {dt:8.3f} s/step fwd+bwd (median-of-{reps}, "
              f"spread {spread:.1%})", flush=True)

    print(f"  zigzag speedup over masked: "
          f"{results['masked'] / results['zigzag']:.2f}x", flush=True)
    print(f"\n  per-rotation work balance (C x C block units per rank, "
          f"C = T/2n = {T // (2 * n)}):")
    print(f"  {'r':>3} {'masked comp':>12} {'masked useful':>14} "
          f"{'zigzag comp':>12} {'zigzag useful':>14}")
    mk = ring_work_schedule(n, "masked")["rotations"]
    zz = ring_work_schedule(n, "zigzag")["rotations"]
    for m, z in zip(mk, zz):
        mu = (f"{m['useful_min']:.0f}" if m["useful_min"] == m["useful_max"]
              else f"{m['useful_min']:.0f}..{m['useful_max']:.0f}")
        print(f"  {m['r']:>3} {m['computed_per_rank']:>12.0f} {mu:>14} "
              f"{z['computed_per_rank']:>12.0f} {z['useful_min']:>14.0f}")
    tm = ring_work_schedule(n, "masked")["total_computed"]
    tz = ring_work_schedule(n, "zigzag")["total_computed"]
    print(f"  total computed: masked {tm:.0f} vs zigzag {tz:.0f} "
          f"(analytic ratio {tm / tz:.2f}x)")
    return results


def main():
    if "--ring" in sys.argv or "--ring-cpu" in sys.argv:
        ring_sweep()
        return
    B, H, D = 1, 8, 64
    rng = np.random.default_rng(0)
    for T, causal in ((16384, False), (16384, True), (32768, True)):
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        for rate in (0.0, 0.1):
            kw = {} if rate == 0 else {"dropout_rate": rate, "dropout_seed": 7}

            def fwd_bwd(q, k, v):
                return jax.grad(lambda q: jnp.sum(flash_attention(
                    q, k, v, causal=causal, **kw).astype(jnp.float32)))(q)

            dt, sp, sc = timeit_slope_stats(fwd_bwd, q, k, v, n1=3, n2=12, reps=3,
                                            max_scale=4)
            print(f"T={T} causal={causal} dropout={rate}: {dt*1e3:7.2f} ms ±{sp:.1%} "
                  f"(x{sc}) fwd+bwd -> {tf(dt, T, B, H, D, causal, True):.0f} TF/s")


if __name__ == "__main__":
    main()
