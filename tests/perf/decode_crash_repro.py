"""Minimal repro for the two relay-worker-killing decode programs (PERF.md).

Round-5 decode measurements found two configurations that reproducibly take
down this rig's axon relay TPU worker (the process serving the TPU over the
relay tunnel) — NOT an XLA OOM: compilation succeeds, the crash lands during
execution of the decode loop:

  1p5b_decode : GPT-2 1.5B, batch 8, greedy 128-token generation over a
                1024-token prompt (the PERF.md decode table's missing row);
  420m_beam   : GPT-2 420M, batch 8, beam-4 128-token generation (runs fine
                with the pre-round-5 cache path at 38.0 tok/s; crashes with
                the in-place dynamic_update_slice cache).

Each case is the SMALLEST program observed to kill the worker: one model, one
prompt, one generate/beam_search call, no timing scaffolding. Run ONE case per
process — a dead relay worker takes every later test in the process down with
it, which is why tier-1 must never collect this file (enforced by
tests/unit/test_tier1_collection.py) and why the pytest entry points carry the
``slow`` marker for explicit runs.

    python tests/perf/decode_crash_repro.py 1p5b_decode
    python tests/perf/decode_crash_repro.py 420m_beam

Exit 0 means the rig survived (fixed relay / different topology); the PERF.md
fencing note tracks which rigs still reproduce.
"""

import sys

import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.slow

T0 = 1024   # prompt length (matches tests/perf/decode_perf.py)
NEW = 128   # generated tokens


def _require_tpu():
    if jax.devices()[0].platform != "tpu":
        raise SystemExit("decode_crash_repro targets the relay TPU worker; "
                         "on CPU/GPU there is nothing to reproduce")


def _model(n_embd, n_layer, n_head):
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=50304, n_positions=T0 + NEW + 8, n_embd=n_embd,
                     n_layer=n_layer, n_head=n_head, use_flash_attention=True)
    model = GPT2Model(cfg)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16) if p.ndim >= 2 else p,
        model.init(jax.random.PRNGKey(0)))
    prompt = jnp.ones((8, T0), jnp.int32)
    return model, params, prompt


@pytest.mark.slow
def test_1p5b_b8_greedy_decode_survives():
    """GPT-2 1.5B, batch 8, 128-token greedy decode: the program whose
    execution kills the relay worker on the round-5 rig."""
    _require_tpu()
    model, params, prompt = _model(n_embd=1600, n_layer=48, n_head=25)
    out = model.generate(params, prompt, NEW)
    assert jax.device_get(out).shape[1] == T0 + NEW


@pytest.mark.slow
def test_420m_b8_beam4_survives():
    """GPT-2 420M, batch 8, beam-4 decode: crashes the relay worker with the
    round-5 in-place KV cache (the pre-round-5 cache path survived)."""
    _require_tpu()
    model, params, prompt = _model(n_embd=1024, n_layer=24, n_head=16)
    seqs, _scores = model.beam_search(params, prompt, NEW, num_beams=4)
    assert jax.device_get(seqs).shape[-1] == T0 + NEW


def main():
    cases = {"1p5b_decode": test_1p5b_b8_greedy_decode_survives,
             "420m_beam": test_420m_b8_beam4_survives}
    if len(sys.argv) != 2 or sys.argv[1] not in cases:
        raise SystemExit(f"usage: python {sys.argv[0]} {{{'|'.join(cases)}}}\n"
                         "(one case per process — a killed worker poisons the rest)")
    cases[sys.argv[1]]()
    print(f"{sys.argv[1]}: survived — relay worker still up")


if __name__ == "__main__":
    main()
