"""Block-sparse vs dense-flash attention on the real TPU (VERDICT r3 next #2 evidence).

BigBird layout at long seq; prints sparse/dense time and the speedup vs the
density-ideal bound. Fence via device_get (axon relay). Run:

    python tests/perf/block_sparse_perf.py [--groups 1,2] [--bwd]
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from deepspeed_tpu.ops.pallas.block_sparse_attention import block_sparse_attention  # noqa: E402
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402
from deepspeed_tpu.ops.sparse_attention.sparsity_config import BigBirdSparsityConfig  # noqa: E402


def time_fn(fn, *args, iters=10):
    fn(*args)
    float(jax.device_get(jnp.sum(fn(*args))))
    best = float("inf")
    for _ in range(2):
        t0 = time.time()
        for _ in range(iters):
            out = fn(*args)
        float(jax.device_get(jnp.sum(out)))
        best = min(best, (time.time() - t0) / iters)
    return best


def main():
    groups = [int(x) for x in
              (sys.argv[sys.argv.index("--groups") + 1].split(",")
               if "--groups" in sys.argv else ["1", "2"])]
    do_bwd = "--bwd" in sys.argv
    B, H, D, BLOCK = 1, 16, 64, 128
    rng = np.random.default_rng(0)
    for T in (4096, 8192):
        cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK)
        layout = cfg.make_layout(T)
        density = float(np.asarray(layout).mean())
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)

        dt_dense = time_fn(jax.jit(lambda q, k, v: flash_attention(q, k, v)), q, k, v)
        print(f"T={T} density={density:.3f} dense-flash fwd: {dt_dense*1e3:.2f} ms "
              f"(ideal sparse: {dt_dense*density*1e3:.2f} ms)")
        for g in groups:
            f = jax.jit(lambda q, k, v, g=g: block_sparse_attention(
                q, k, v, layout, BLOCK, group=g))
            dt = time_fn(f, q, k, v)
            print(f"  group={g}: {dt*1e3:.2f} ms  speedup {dt_dense/dt:.2f}x "
                  f"(ideal {1/density:.1f}x)")
            if do_bwd:
                gr = jax.jit(jax.grad(lambda q, k, v, g=g: jnp.sum(
                    block_sparse_attention(q, k, v, layout, BLOCK, group=g)
                    .astype(jnp.float32))))
                dt_b = time_fn(gr, q, k, v)
                gd = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v).astype(jnp.float32))))
                dt_db = time_fn(gd, q, k, v)
                print(f"  group={g} bwd(dq-only-grad fwd+bwd): sparse {dt_b*1e3:.2f} ms "
                      f"vs dense {dt_db*1e3:.2f} ms -> {dt_db/dt_b:.2f}x")


if __name__ == "__main__":
    main()
