"""Block-sparse vs dense-flash attention on the real TPU (slope-timed; see
devtime.py — host-loop timings over the axon relay are fence-noise).

BigBird layout at long seq; prints sparse/dense time and the speedup vs the
density-ideal bound.

    python tests/perf/block_sparse_perf.py [--groups 1,2] [--bwd] [--local W]

``--local W`` swaps BigBird for a W-block sliding-window band (union-friendly,
no global rows) — the gap-decomposition probe PERF.md cites.
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from devtime import timeit_slope_stats  # noqa: E402
from deepspeed_tpu.ops.pallas.block_sparse_attention import block_sparse_attention  # noqa: E402
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402
from deepspeed_tpu.ops.sparse_attention.sparsity_config import BigBirdSparsityConfig  # noqa: E402


def main():
    groups = [int(x) for x in
              (sys.argv[sys.argv.index("--groups") + 1].split(",")
               if "--groups" in sys.argv else ["1", "2"])]
    do_bwd = "--bwd" in sys.argv
    # --local W: sliding-window band of W blocks instead of BigBird — union-
    # friendly (adjacent q-rows share almost the whole block set, no global
    # rows in every cell's union), isolating pattern structure from kernel
    # efficiency in the gap to the density-ideal
    local_w = (int(sys.argv[sys.argv.index("--local") + 1])
               if "--local" in sys.argv else 0)
    B, H, D = 1, 16, 64
    BLOCK = (int(sys.argv[sys.argv.index("--block") + 1])
             if "--block" in sys.argv else 128)
    rng = np.random.default_rng(0)
    for T in (4096, 8192):
        if local_w:
            nb = T // BLOCK
            lay = np.zeros((H, nb, nb), np.int64)
            for i in range(nb):
                lay[:, i, max(0, i - local_w + 1):i + 1] = 1  # causal-style band
            layout = lay  # layouts are host-side numpy by module contract
        else:
            cfg = BigBirdSparsityConfig(num_heads=H, block=BLOCK)
            layout = cfg.make_layout(T)
        density = float(np.asarray(layout).mean())
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.bfloat16)
        n1, n2 = (50, 250) if T <= 4096 else (10, 60)

        # median +/- spread with automatic iteration escalation: the sub-ms sparse
        # kernels need the spread pinned <10% for a reproducible speedup number
        # (VERDICT r3 #5 — round-3 quoted 1.7-3.7x bands from best-of-reps)
        dt_dense, sp_d, sc_d = timeit_slope_stats(
            lambda q, k, v: flash_attention(q, k, v), q, k, v, n1=n1, n2=n2)
        print(f"T={T} density={density:.3f} dense-flash fwd: {dt_dense*1e3:.3f} ms "
              f"±{sp_d:.1%} (x{sc_d}) "
              f"(density-ideal sparse: {dt_dense*density*1e3:.3f} ms)")
        for g in groups:
            dt, sp, sc = timeit_slope_stats(lambda q, k, v, g=g: block_sparse_attention(
                q, k, v, layout, BLOCK, group=g), q, k, v, n1=n1, n2=n2)
            print(f"  group={g}: {dt*1e3:.3f} ms ±{sp:.1%} (x{sc})  "
                  f"speedup {dt_dense/dt:.2f}x (ideal {1/density:.1f}x)")
            if do_bwd:
                gs = lambda q, k, v, g=g: jax.grad(lambda q: jnp.sum(
                    block_sparse_attention(q, k, v, layout, BLOCK, group=g)
                    .astype(jnp.float32)))(q)
                gd = lambda q, k, v: jax.grad(lambda q: jnp.sum(
                    flash_attention(q, k, v).astype(jnp.float32)))(q)
                dt_b, sp_b, _ = timeit_slope_stats(gs, q, k, v, n1=5, n2=30)
                dt_db, sp_db, _ = timeit_slope_stats(gd, q, k, v, n1=5, n2=30)
                print(f"  group={g} fwd+bwd: sparse {dt_b*1e3:.3f} ms ±{sp_b:.1%} vs "
                      f"dense {dt_db*1e3:.3f} ms ±{sp_db:.1%} -> {dt_db/dt_b:.2f}x")


if __name__ == "__main__":
    main()
