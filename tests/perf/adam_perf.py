"""CPU Adam microbenchmark (analog of reference tests/perf/adam_test.py: 1B-param
timing). Run directly: python tests/perf/adam_perf.py [numel]."""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam  # noqa: E402


def main():
    numel = int(sys.argv[1]) if len(sys.argv) > 1 else 64 * 1024 * 1024
    params = {"w": np.zeros(numel, np.float32)}
    rng = np.random.default_rng(0)
    g = rng.normal(size=numel).astype(np.float32)

    native = DeepSpeedCPUAdam(params)
    fallback = DeepSpeedCPUAdam(params)
    fallback._lib = None

    def bench(opt, label, iters=5):
        opt.step(g, step=1, lr=1e-3)  # warm
        t0 = time.perf_counter()
        for i in range(iters):
            opt.step(g, step=i + 2, lr=1e-3)
        dt = (time.perf_counter() - t0) / iters
        print(f"{label:8s}: {dt * 1e3:8.2f} ms/step  "
              f"({numel / dt / 1e9:6.2f} Gelem/s)")
        return dt

    t_np = bench(fallback, "numpy")
    t_nat = None
    if native._lib is not None:
        t_nat = bench(native, "native")
        print(f"native speedup vs numpy: {t_np / t_nat:.1f}x")
    else:
        print("native kernel unavailable")

    # torch.optim.Adam on the same host (the reference claims DeepSpeedCPUAdam is
    # 5-7x faster than torch Adam, docs/_tutorials/zero-offload.md:9)
    try:
        import torch
    except ImportError:
        return
    tp = torch.nn.Parameter(torch.zeros(numel))
    topt = torch.optim.Adam([tp], lr=1e-3)
    tg = torch.from_numpy(g)
    tp.grad = tg
    topt.step()  # warm (state alloc)
    t0 = time.perf_counter()
    iters = 5
    for _ in range(iters):
        topt.step()
    t_torch = (time.perf_counter() - t0) / iters
    print(f"{'torch':8s}: {t_torch * 1e3:8.2f} ms/step  "
          f"({numel / t_torch / 1e9:6.2f} Gelem/s)")
    if t_nat is not None:
        print(f"native speedup vs torch.optim.Adam: {t_torch / t_nat:.1f}x")


if __name__ == "__main__":
    main()
