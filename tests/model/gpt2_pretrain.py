#!/usr/bin/env python
"""GPT-2 functional pretraining driver — the integration-test workload.

Analog of the reference's ``tests/model/Megatron_GPT2`` suite (``ds_gpt2_test.sh`` builds a
Megatron pretrain command line; ``test_common.py:69-98`` parses the resulting logs). Here the
workload is our own tiny GPT-2 launched as a subprocess by ``run_func_test.py`` /
``run_checkpoint_test.py`` with a ``--deepspeed_config`` JSON, training on deterministic
synthetic data over an 8-virtual-device CPU mesh, printing parseable per-step lines:

    step: N loss: X lr: Y

Supports checkpoint save (``--save-dir`` + ``--save-interval``) and resume (``--load-dir``)
so the checkpoint test can compare an interrupted-and-resumed run against a straight run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from workload_env import setup  # noqa: E402  (must precede jax backend init)

jax = setup()

import argparse  # noqa: E402

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model  # noqa: E402


def get_args():
    p = argparse.ArgumentParser(description="tiny GPT-2 pretraining (integration tests)")
    p.add_argument("--steps", type=int, default=8, help="optimizer steps to run")
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--vocab-size", type=int, default=64)
    p.add_argument("--seq", type=int, default=16)
    p.add_argument("--n-layer", type=int, default=2)
    p.add_argument("--n-embd", type=int, default=32)
    p.add_argument("--n-head", type=int, default=2)
    p.add_argument("--save-dir", type=str, default=None)
    p.add_argument("--save-interval", type=int, default=0,
                   help="save a checkpoint every N steps (0 = never)")
    p.add_argument("--load-dir", type=str, default=None,
                   help="resume from the latest checkpoint in this directory")
    p.add_argument("--corpus", type=str, default=None,
                   help="path to a natural-text file: train next-BYTE prediction on "
                        "real text (vocab is forced to 256) instead of the synthetic "
                        "stream — the real-data convergence gate")
    p = deepspeed_tpu.add_config_arguments(p)
    return p.parse_args()


def build_dataset(args, total_steps, global_batch, gas):
    """Deterministic learnable LM stream, generated in full so a resumed run sees the
    exact same batches for steps it replays (same role as Megatron's seeded dataloader)."""
    micro = global_batch // gas
    rng = np.random.default_rng(args.seed)
    toks = rng.integers(0, args.vocab_size,
                        size=(total_steps, gas, micro, args.seq)).astype(np.int32)
    # Make every odd position predictable from the previous token so loss can fall fast.
    toks[..., 1::2] = (toks[..., 0::2] + 1) % args.vocab_size
    labels = np.roll(toks, -1, axis=-1)
    return toks, labels


def build_corpus_dataset(args, total_steps, global_batch, gas):
    """Deterministic batches of REAL text: random windows of the corpus bytes with
    true next-byte labels (no synthetic structure — convergence here means the
    model is learning natural-language statistics)."""
    with open(args.corpus, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8).astype(np.int32)
    assert len(data) > args.seq + 1, "corpus smaller than one window"
    micro = global_batch // gas
    rng = np.random.default_rng(args.seed)
    starts = rng.integers(0, len(data) - args.seq - 1,
                          size=(total_steps, gas, micro))
    idx = starts[..., None] + np.arange(args.seq)
    return data[idx], data[idx + 1]


def main():
    args = get_args()
    if args.corpus:
        args.vocab_size = 256  # byte-level LM over the natural text
    cfg = GPT2Config(vocab_size=args.vocab_size, n_positions=args.seq, n_embd=args.n_embd,
                     n_layer=args.n_layer, n_head=args.n_head)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(args.seed))

    engine, _, _, _ = deepspeed_tpu.initialize(args=args, model=model,
                                               model_parameters=params)

    start_step = 0
    if args.load_dir:
        path, _client = engine.load_checkpoint(args.load_dir)
        assert path is not None, f"no checkpoint found in {args.load_dir}"
        start_step = engine.global_steps
        print(f"resumed_from: {start_step}", flush=True)

    gas = engine.gradient_accumulation_steps()
    build = build_corpus_dataset if args.corpus else build_dataset
    toks, labels = build(args, args.steps, engine.train_batch_size(), gas)

    for step in range(start_step, args.steps):
        total = 0.0
        for m in range(gas):
            loss = engine(toks[step, m], labels[step, m])
            engine.backward(loss)
            total += float(jax.device_get(loss))
        engine.step()
        lr = engine.get_lr()
        print(f"step: {step + 1} loss: {total / gas:.6f} lr: {lr[0] if lr else 0.0:.8f}",
              flush=True)
        if args.save_dir and args.save_interval and (step + 1) % args.save_interval == 0:
            engine.save_checkpoint(args.save_dir)
            print(f"saved_at: {step + 1}", flush=True)

    print("training_complete", flush=True)


if __name__ == "__main__":
    main()
