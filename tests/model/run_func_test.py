"""GPT-2 functional integration tests: subprocess runs sweeping DeepSpeed JSON configs.

Analog of reference ``tests/model/Megatron_GPT2/run_func_test.py``: each case launches the
real workload (``gpt2_pretrain.py``) under a different ``ds_config_func_*.json`` and checks
(a) the run completes, (b) loss decreases, and (c) ZeRO stages agree with the no-ZeRO
baseline on identical data/seed (the reference checks cross-config loss parity the same
way, via ``check_parity`` over parsed train-loss logs).
"""

import math

import pytest

from .test_common import load_config, run_gpt2

STEPS = 8

CONFIGS = [
    "ds_config_func_bs8_no_zero.json",
    "ds_config_func_bs8_zero1.json",
    "ds_config_func_bs8_zero2.json",
    "ds_config_func_bs8_zero3.json",
    "ds_config_func_bs16_zero2.json",
    "ds_config_func_bs16_zero2_gas2.json",
    "ds_config_func_bs8_zero2_offload.json",
    "ds_config_func_bs8_fp16.json",
    "ds_config_func_scheduler.json",
]

_cache = {}


def _run(name, tmp_path_factory, extra_args=()):
    """One subprocess per (config, args) per session; parity tests reuse cached records."""
    key = (name, tuple(map(str, extra_args)))
    if key not in _cache:
        workdir = tmp_path_factory.mktemp(name.replace(".json", ""))
        records, proc = run_gpt2(load_config(name), workdir, steps=STEPS,
                                 extra_args=extra_args, name=name.replace(".json", ""))
        _cache[key] = (records, proc.stdout)
    return _cache[key]


@pytest.mark.parametrize("config_name", CONFIGS)
def test_loss_decreases(config_name, tmp_path_factory):
    records, stdout = _run(config_name, tmp_path_factory)
    assert len(records) == STEPS, f"expected {STEPS} step lines, got {len(records)}\n{stdout}"
    losses = [r["loss"] for r in records]
    assert all(math.isfinite(l) for l in losses), f"non-finite loss: {losses}"
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    assert "training_complete" in stdout


def test_zero_stages_agree(tmp_path_factory):
    """ZeRO-1/2/3 and ZeRO-2+offload are pure memory optimizations: same data + seed
    must give the same loss trajectory as the unpartitioned baseline (fp32 exact-ish)."""
    base = [r["loss"] for r in _run("ds_config_func_bs8_no_zero.json", tmp_path_factory)[0]]
    for name in ("ds_config_func_bs8_zero1.json", "ds_config_func_bs8_zero2.json",
                 "ds_config_func_bs8_zero3.json",
                 "ds_config_func_bs8_zero2_offload.json"):
        other = [r["loss"] for r in _run(name, tmp_path_factory)[0]]
        assert other == pytest.approx(base, rel=2e-3, abs=2e-3), \
            f"{name} diverged from no-ZeRO baseline:\n  base={base}\n  got ={other}"


def test_gas_changes_only_batch_schedule(tmp_path_factory):
    """gas=2 at bs16 consumes the identical token stream per optimizer step as gas=1 at
    bs16 (the dataset fills C-order from one seed), so the loss curves must match."""
    base = [r["loss"] for r in _run("ds_config_func_bs16_zero2.json", tmp_path_factory)[0]]
    gas2 = [r["loss"] for r in _run("ds_config_func_bs16_zero2_gas2.json", tmp_path_factory)[0]]
    assert gas2 == pytest.approx(base, rel=2e-3, abs=2e-3), \
        f"gas=2 diverged:\n  base={base}\n  gas2={gas2}"


def test_scheduler_warmup_ramps_lr(tmp_path_factory):
    records, _ = _run("ds_config_func_scheduler.json", tmp_path_factory)
    lrs = [r["lr"] for r in records]
    # WarmupLR: monotone non-decreasing ramp to warmup_max_lr over warmup_num_steps.
    assert all(b >= a for a, b in zip(lrs, lrs[1:])), f"lr not ramping: {lrs}"
    assert lrs[0] < lrs[5], f"no warmup observed: {lrs}"
    assert lrs[-1] == pytest.approx(0.003, rel=1e-6)
