"""Checkpoint save/resume integration test across real subprocess boundaries.

Analog of reference ``tests/model/Megatron_GPT2/run_checkpoint_test.py``: train N steps in
one process saving midway, then resume in a FRESH process from the checkpoint and verify
the post-resume loss trajectory exactly tracks an uninterrupted run (engine + optimizer +
LR-scheduler state all round-trip through disk)."""

import pytest

from .test_common import load_config, run_gpt2

STEPS = 8
SAVE_AT = 4


@pytest.mark.parametrize("config_name", [
    "ds_config_func_bs8_zero2.json",
    # scheduler resume coverage rides the zero2 variant in tier-1; the second
    # ~35s subprocess pair is `slow` (tier-1 870s cap)
    pytest.param("ds_config_func_scheduler.json", marks=pytest.mark.slow)])
def test_resume_matches_straight_run(config_name, tmp_path, tmp_path_factory):
    cfg = load_config(config_name)
    ckpt = tmp_path / "ckpt"

    straight, _ = run_gpt2(cfg, tmp_path / "straight", steps=STEPS, name="straight")

    _first, _ = run_gpt2(cfg, tmp_path / "first", steps=SAVE_AT, name="first",
                         extra_args=["--save-dir", ckpt, "--save-interval", SAVE_AT])
    resumed, proc = run_gpt2(cfg, tmp_path / "resumed", steps=STEPS, name="resumed",
                             extra_args=["--load-dir", ckpt])

    assert f"resumed_from: {SAVE_AT}" in proc.stdout
    assert [r["step"] for r in resumed] == list(range(SAVE_AT + 1, STEPS + 1))

    tail_straight = [r for r in straight if r["step"] > SAVE_AT]
    assert [r["loss"] for r in resumed] == pytest.approx(
        [r["loss"] for r in tail_straight], rel=1e-4, abs=1e-4), \
        f"resumed trajectory diverged:\n  straight={tail_straight}\n  resumed={resumed}"
    assert [r["lr"] for r in resumed] == pytest.approx(
        [r["lr"] for r in tail_straight], rel=1e-6), "LR schedule state did not resume"
