#!/usr/bin/env python
"""BERT SQuAD-style fine-tuning driver — the BingBertSquad integration workload.

Analog of the reference's ``tests/model/BingBertSquad`` e2e scripts: fine-tune a tiny
BERT with a span-extraction QA head through the engine under a ``--deepspeed_config``
JSON, on synthetic learnable QA data, printing the same parseable
``step: N loss: X lr: Y`` lines as ``gpt2_pretrain.py``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from workload_env import setup  # noqa: E402  (must precede jax backend init)

jax = setup()

import argparse  # noqa: E402

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models.bert import BertConfig, BertForQuestionAnswering  # noqa: E402


def get_args():
    p = argparse.ArgumentParser(description="tiny BERT QA fine-tune (integration tests)")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--seed", type=int, default=29)
    p.add_argument("--vocab-size", type=int, default=128)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--heads", type=int, default=2)
    p = deepspeed_tpu.add_config_arguments(p)
    return p.parse_args()


def build_dataset(args, steps, batch):
    """Learnable synthetic QA: the answer span starts at the position of token 1 and
    ends at the position of token 2 (planted once per sequence)."""
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(3, args.vocab_size, size=(steps, batch, args.seq)).astype(np.int32)
    starts = rng.integers(1, args.seq // 2, size=(steps, batch)).astype(np.int32)
    ends = (starts + rng.integers(1, args.seq // 2, size=(steps, batch))).astype(np.int32)
    for s in range(steps):
        for b in range(batch):
            ids[s, b, starts[s, b]] = 1
            ids[s, b, ends[s, b]] = 2
    return ids, starts, ends


def main():
    args = get_args()
    cfg = BertConfig(vocab_size=args.vocab_size, hidden_size=args.hidden,
                     num_hidden_layers=args.layers, num_attention_heads=args.heads,
                     max_position_embeddings=args.seq,
                     intermediate_size=4 * args.hidden,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForQuestionAnswering(cfg)
    params = model.init(jax.random.PRNGKey(args.seed))

    engine, _, _, _ = deepspeed_tpu.initialize(args=args, model=model,
                                               model_parameters=params)
    gas = engine.gradient_accumulation_steps()
    assert gas == 1, "this driver keeps gas=1 (span batches are per-step)"
    ids, starts, ends = build_dataset(args, args.steps, engine.train_batch_size())

    for step in range(args.steps):
        loss = engine(ids[step], starts[step], ends[step])
        engine.backward(loss)
        engine.step()
        lr = engine.get_lr()
        print(f"step: {step + 1} loss: {float(jax.device_get(loss)):.6f} "
              f"lr: {lr[0] if lr else 0.0:.8f}", flush=True)

    print("training_complete", flush=True)


if __name__ == "__main__":
    main()
