"""Shared environment bootstrap for subprocess workload drivers.

Must be imported (and ``setup()`` called) BEFORE jax initializes a backend: forces the
virtual multi-device CPU platform despite this environment's sitecustomize pinning a real
TPU platform (see tests/conftest.py for the full story)."""

import os
import sys


def setup():
    """Configure the CPU test platform and repo import path; returns the jax module."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        n = os.environ.get("DS_TEST_CPU_DEVICES", "8")
        os.environ["XLA_FLAGS"] = flags + f" --xla_force_host_platform_device_count={n}"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
    return jax
