"""BERT QA fine-tuning integration tests (BingBertSquad analog).

Mirrors the reference's ``tests/model/BingBertSquad/test_e2e_squad.py`` intent: run the
fine-tuning workload as a subprocess under fp16 and ZeRO configs and check convergence.
"""

import math
import os

import pytest

from .test_common import THIS_DIR, load_config, run_workload

SCRIPT = os.path.join(THIS_DIR, "bert_squad_finetune.py")
STEPS = 8


def _run_bert(config_name, tmp_path):
    records, proc = run_workload(SCRIPT, load_config(config_name), tmp_path,
                                 steps=STEPS, name="bert")
    return records, proc.stdout


@pytest.mark.parametrize("config_name", [
    "ds_config_func_bs8_zero2.json",
    pytest.param("ds_config_func_bs8_fp16.json",
                 marks=pytest.mark.slow)])  # ~16s subprocess; tier-1 cap
def test_bert_qa_finetune_converges(config_name, tmp_path):
    records, stdout = _run_bert(config_name, tmp_path)
    assert len(records) == STEPS, stdout
    losses = [r["loss"] for r in records]
    assert all(math.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], f"QA loss did not decrease: {losses}"
    assert "training_complete" in stdout
