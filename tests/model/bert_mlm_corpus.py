#!/usr/bin/env python
"""BERT masked-LM pretraining on the REAL natural-text corpus — the MLM half of
the real-data convergence gate (VERDICT r4 #9; the reference's analog workload is
the BingBertSquad/Megatron real-data suites, tests/model/BingBertSquad).

Byte-level MLM over tests/model/data/corpus.txt: 15% of byte positions are
replaced by a [MASK] id (vocab 256 bytes + 1 mask token) and the model predicts
the original byte; labels are -100 elsewhere. Prints the same parseable
``step: N loss: X lr: Y`` lines as gpt2_pretrain.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from workload_env import setup  # noqa: E402  (must precede jax backend init)

jax = setup()

import argparse  # noqa: E402

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM  # noqa: E402

MASK_ID = 256


def get_args():
    p = argparse.ArgumentParser(description="byte-level BERT MLM on real text")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--seed", type=int, default=31)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--corpus", type=str, required=True)
    p = deepspeed_tpu.add_config_arguments(p)
    return p.parse_args()


def build_dataset(args, steps, batch):
    with open(args.corpus, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8).astype(np.int32)
    rng = np.random.default_rng(args.seed)
    starts = rng.integers(0, len(data) - args.seq, size=(steps, batch))
    ids = data[starts[..., None] + np.arange(args.seq)]
    labels = np.full_like(ids, -100)
    masked = rng.random(ids.shape) < 0.15
    labels[masked] = ids[masked]
    ids = np.where(masked, MASK_ID, ids)
    return ids, labels


def main():
    args = get_args()
    cfg = BertConfig(vocab_size=MASK_ID + 1, hidden_size=args.hidden,
                     num_hidden_layers=args.layers, num_attention_heads=args.heads,
                     max_position_embeddings=args.seq,
                     intermediate_size=4 * args.hidden,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForMaskedLM(cfg)
    params = model.init(jax.random.PRNGKey(args.seed))

    engine, _, _, _ = deepspeed_tpu.initialize(args=args, model=model,
                                               model_parameters=params)
    gas = engine.gradient_accumulation_steps()
    assert gas == 1, "this driver keeps gas=1"
    ids, labels = build_dataset(args, args.steps, engine.train_batch_size())

    for step in range(args.steps):
        loss = engine(ids[step], labels[step])
        engine.backward(loss)
        engine.step()
        lr = engine.get_lr()
        print(f"step: {step + 1} loss: {float(jax.device_get(loss)):.6f} "
              f"lr: {lr[0] if lr else 0.0:.8f}", flush=True)

    print("training_complete", flush=True)


if __name__ == "__main__":
    main()
