#!/usr/bin/env python
"""GPT-2 throughput comparison runner (reference ``run_perf_baseline.py`` /
``run_perf_test.py``): measures samples/sec for each ``ds_config_perf_*.json``, records a
baseline JSON, and on later runs compares against it.

Not collected by pytest (ignored via tests/model/conftest.py — perf numbers are
machine-dependent); run manually:

    python tests/model/run_perf_test.py --baseline        # record tests/model/perf_baseline.json
    python tests/model/run_perf_test.py                   # compare vs the recorded baseline

By default the workload driver pins the 8-virtual-device CPU platform, so numbers are
regression-shaped only; export JAX_PLATFORMS=tpu to measure the real chip (the driver's
setdefault honors it).
"""

import argparse
import glob
import json
import os
import subprocess
import sys
import time

THIS_DIR = os.path.dirname(os.path.abspath(__file__))
BASELINE_PATH = os.path.join(THIS_DIR, "perf_baseline.json")
STEPS = 12
TOLERANCE = 0.10   # fail if >10% slower than baseline (reference compares the same way)


def measure(config_path):
    cmd = [sys.executable, os.path.join(THIS_DIR, "gpt2_pretrain.py"), "--deepspeed",
           "--deepspeed_config", config_path, "--steps", str(STEPS)]
    t0 = time.time()
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    wall = time.time() - t0
    with open(config_path) as f:
        batch = json.load(f)["train_batch_size"]
    # crude but stable: amortized samples/sec including compile (reference parses
    # Megatron's per-iteration logs; our driver prints per-step lines without timings)
    return batch * STEPS / wall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--baseline", action="store_true",
                    help="record results as the new baseline instead of comparing")
    args = ap.parse_args()

    results = {}
    for cfg in sorted(glob.glob(os.path.join(THIS_DIR, "ds_config_perf_*.json"))):
        name = os.path.basename(cfg)
        results[name] = round(measure(cfg), 2)
        print(f"{name}: {results[name]} samples/sec")

    if args.baseline or not os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH, "w") as f:
            json.dump(results, f, indent=2)
        print(f"baseline written to {BASELINE_PATH}")
        return 0

    with open(BASELINE_PATH) as f:
        baseline = json.load(f)
    rc = 0
    for name, sps in results.items():
        base = baseline.get(name)
        if base is None:
            continue
        ratio = sps / base
        status = "OK" if ratio >= 1.0 - TOLERANCE else "REGRESSION"
        if status == "REGRESSION":
            rc = 1
        print(f"{name}: {sps} vs baseline {base} ({ratio:.2%}) {status}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
