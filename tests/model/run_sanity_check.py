#!/usr/bin/env python
"""Top-level sanity runner (analog of reference ``tests/model/run_sanity_check.py`` +
``basic_install_test.py``): import the package, check version/ops availability, and run
one tiny end-to-end training subprocess. Usable both as a pytest module and a script."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))


def test_import_and_version():
    import deepspeed_tpu
    assert deepspeed_tpu.__version__
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine  # noqa: F401
    from deepspeed_tpu.ops.sparse_attention import SparseSelfAttention  # noqa: F401
    from deepspeed_tpu.ops.transformer import DeepSpeedTransformerLayer  # noqa: F401
    from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule  # noqa: F401
    from deepspeed_tpu.launcher.runner import fetch_hostfile  # noqa: F401


def test_native_cpu_adam_builds():
    from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
    assert DeepSpeedCPUAdam is not None


def test_one_training_run(tmp_path):
    from .test_common import load_config, run_gpt2
    records, _ = run_gpt2(load_config("ds_config_func_bs8_zero2.json"), tmp_path,
                          steps=2, name="sanity")
    assert len(records) == 2


if __name__ == "__main__":
    import pytest
    raise SystemExit(pytest.main([__file__, "-v"]))
