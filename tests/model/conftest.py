# keep the manual perf runner out of pytest collection (its filename matches the
# default *_test.py glob for reference-name parity, but it is a CLI tool)
collect_ignore = ["run_perf_test.py"]
