"""Shared harness for subprocess model tests.

Mirrors the reference's ``tests/model/Megatron_GPT2/test_common.py:69-98``: build a command
line, run the workload as a real subprocess (fresh JAX runtime, real launcher-style entry),
and parse per-step losses/LRs out of its stdout.
"""

import json
import os
import re
import subprocess
import sys

THIS_DIR = os.path.dirname(os.path.abspath(__file__))
SCRIPT = os.path.join(THIS_DIR, "gpt2_pretrain.py")

_STEP_RE = re.compile(r"^step: (\d+) loss: ([\d.eE+-]+) lr: ([\d.eE+-]+)$", re.M)


def load_config(name):
    with open(os.path.join(THIS_DIR, name)) as f:
        return json.load(f)


def parse_steps(stdout):
    """-> list of dicts {step, loss, lr} in step order."""
    return [{"step": int(m.group(1)), "loss": float(m.group(2)), "lr": float(m.group(3))}
            for m in _STEP_RE.finditer(stdout)]


def run_workload(script, config, workdir, steps=8, extra_args=(), name="run", timeout=600):
    """Write `config` to JSON, launch `script` as a subprocess, parse its step lines.

    Returns (records, completed_process). Raises AssertionError with full output on a
    nonzero exit (the reference's harness turns subprocess failures into test failures
    the same way, tests/unit/common.py:60-84).
    """
    os.makedirs(workdir, exist_ok=True)
    cfg_path = os.path.join(str(workdir), f"{name}.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f, indent=2)
    cmd = [sys.executable, script, "--deepspeed", "--deepspeed_config", cfg_path,
           "--steps", str(steps), *map(str, extra_args)]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout, env=env)
    assert proc.returncode == 0, (
        f"workload failed (rc={proc.returncode})\ncmd: {' '.join(cmd)}\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr[-4000:]}")
    records = parse_steps(proc.stdout)
    return records, proc


def run_gpt2(config, workdir, steps=8, extra_args=(), name="run", timeout=600):
    return run_workload(SCRIPT, config, workdir, steps=steps, extra_args=extra_args,
                        name=name, timeout=timeout)
