"""Real-corpus convergence gate (VERDICT r4 #9).

Every other model-suite workload trains on synthetic streams; this module pins
that the framework trains models on NATURAL text to a quality threshold — the
analog of the reference's real-data Megatron-GPT2 / BingBertSquad model tests
(reference tests/model/Megatron_GPT2/run_func_test.py, BingBertSquad/run_tests.sh).

Corpus: tests/model/data/corpus.txt — 154 KB of genuine natural-English prose
(freely-redistributable license texts), committed so the gate is self-contained.
Byte-level modeling (vocab 256/257): no external tokenizer needed.

Thresholds were calibrated on the 8-virtual-device CPU mesh with margin over the
observed curves (GPT-2: 5.53 -> ~2.74 nats/byte by step 120; BERT-MLM:
5.59 -> ~3.1-3.5 band by step 100) — loose enough for numeric jitter, tight
enough that a model failing to learn real-text statistics (loss stuck near the
uniform baseline ln(256) = 5.55) fails loudly.
"""

import math
import os

import numpy as np
import pytest

from .test_common import THIS_DIR, parse_steps, run_gpt2, run_workload

CORPUS = os.path.join(THIS_DIR, "data", "corpus.txt")
BERT_SCRIPT = os.path.join(THIS_DIR, "bert_mlm_corpus.py")

GPT2_ARGS = ("--seq", "128", "--n-layer", "2", "--n-embd", "128", "--n-head", "4",
             "--corpus", CORPUS)


def corpus_config(**over):
    cfg = {"train_batch_size": 16, "steps_per_print": 1000,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    cfg.update(over)
    return cfg


def test_corpus_is_natural_text():
    """The gate is only meaningful on real language: assert the committed corpus
    looks like English prose, not binary or synthetic noise."""
    with open(CORPUS, "rb") as f:
        data = f.read()
    assert len(data) > 100_000
    text = data.decode("utf-8")
    words = text.split()
    # natural English: common function words appear frequently
    lower = text.lower()
    for w in (" the ", " of ", " and ", " to "):
        assert lower.count(w) > 100, w
    # bytes-per-word in a natural-language band
    assert 4 < len(data) / len(words) < 9


@pytest.mark.slow
def test_gpt2_trains_on_real_text_to_threshold(tmp_path):
    """Next-byte GPT-2 on natural English reaches < 3.05 nats/byte (~4.4 bits)
    within 120 steps — far below the uniform 5.55 and the unigram ~4.2."""
    recs, _ = run_gpt2(corpus_config(zero_optimization={"stage": 2}), tmp_path,
                       steps=120, extra_args=GPT2_ARGS, name="corpus_z2",
                       timeout=900)
    assert len(recs) == 120
    first, tail = recs[0]["loss"], np.mean([r["loss"] for r in recs[-10:]])
    assert first > 4.5, f"did not start from scratch (first loss {first})"
    assert tail < 3.05, f"failed to learn natural-text statistics (tail {tail:.3f})"


@pytest.mark.slow
def test_cross_stage_parity_on_real_text(tmp_path):
    """ZeRO stages are an implementation detail: stage 0 and stage 2 on identical
    real-text batches/seed must produce the same loss trajectory (the reference's
    check_parity discipline, run_func_test.py:6-7 — here on natural data)."""
    k = 20
    recs0, _ = run_gpt2(corpus_config(), tmp_path, steps=k,
                        extra_args=GPT2_ARGS, name="corpus_z0", timeout=900)
    recs2, _ = run_gpt2(corpus_config(zero_optimization={"stage": 2}), tmp_path,
                        steps=k, extra_args=GPT2_ARGS, name="corpus_z2p", timeout=900)
    l0 = [r["loss"] for r in recs0]
    l2 = [r["loss"] for r in recs2]
    np.testing.assert_allclose(l0, l2, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_bert_mlm_trains_on_real_text_to_threshold(tmp_path):
    """Byte-level BERT masked-LM on natural English: mean of the last 20 steps
    < 3.7 nats on masked positions (uniform baseline ln(257) = 5.55) and at
    least 1.5 nats below the from-scratch first step."""
    recs, _ = run_workload(BERT_SCRIPT, corpus_config(zero_optimization={"stage": 2}),
                           tmp_path, steps=100, extra_args=("--corpus", CORPUS),
                           name="bert_corpus", timeout=900)
    assert len(recs) == 100
    first, tail = recs[0]["loss"], np.mean([r["loss"] for r in recs[-20:]])
    assert first > 4.5
    assert tail < 3.7, f"failed to learn masked-byte statistics (tail {tail:.3f})"
    assert tail < first - 1.5
