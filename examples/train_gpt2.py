#!/usr/bin/env python
"""Minimal GPT-2 pretraining example (the DeepSpeedExamples/Megatron-LM analog).

Synthetic next-token data so it runs anywhere; swap ``synthetic_documents`` for a
real token stream. One chip or a mesh — the engine shards the batch over the
``data`` axis either way.

    python examples/train_gpt2.py --steps 20
    python examples/train_gpt2.py --zero 3                 # ZeRO-3 param sharding
    python examples/train_gpt2.py --sparse                 # BigBird block-sparse attention
"""

import argparse
import numpy as np


def synthetic_documents(rng, vocab, batch, seq):
    """Markov-ish synthetic tokens (learnable structure, unlike uniform noise)."""
    base = rng.integers(0, vocab, size=(batch, (seq + 7) // 8)).astype(np.int32)
    toks = np.repeat(base, 8, axis=1)[:, :seq]
    noise = rng.random(toks.shape) < 0.1
    toks[noise] = rng.integers(0, vocab, size=int(noise.sum()))
    return toks


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--width", type=int, default=256)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=8192)
    p.add_argument("--zero", type=int, default=2, choices=(0, 1, 2, 3))
    p.add_argument("--fp32", action="store_true",
               help="disable the default bf16 compute policy")
    p.add_argument("--sparse", action="store_true",
                   help="BigBird block-sparse attention (seq must be a multiple "
                        "of the attention block: 128 on TPU, 16 elsewhere)")
    args = p.parse_args()

    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    sparse_cfg = None
    if args.sparse:
        from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
        # the compiled TPU kernel needs 128-multiple blocks; BigBird's default
        # window needs >= 4 block rows. CPU interpret mode accepts small blocks.
        block = 128 if jax.default_backend() == "tpu" else 16
        if args.seq < 4 * block or args.seq % block:
            p.error(f"--sparse on this backend needs --seq a multiple of {block}"
                    f" and >= {4 * block}")
        sparse_cfg = BigBirdSparsityConfig(num_heads=args.heads, block=block)

    cfg = GPT2Config(vocab_size=args.vocab, n_positions=args.seq,
                     n_embd=args.width, n_layer=args.layers, n_head=args.heads,
                     use_flash_attention=jax.default_backend() == "tpu"
                     and not args.sparse,
                     sparse_attention=sparse_cfg)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config_params={
            "train_batch_size": args.batch,
            "steps_per_print": 5,
            "bf16": {"enabled": not args.fp32},
            "optimizer": {"type": "Adam", "params": {"lr": 3e-4}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_num_steps": 10}},
            "zero_optimization": {"stage": args.zero},
        })

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        tokens = synthetic_documents(rng, args.vocab, args.batch, args.seq)
        labels = np.roll(tokens, -1, axis=1)
        labels[:, -1] = -100  # no next token for the last position (ignored)
        loss = engine(tokens, labels)
        engine.backward(loss)
        engine.step()
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss):.4f}")

    # generation from the trained model (greedy + nucleus)
    prompt = synthetic_documents(rng, args.vocab, 1, 16)
    out = model.generate(engine.params, prompt, max_new_tokens=16)
    print("greedy continuation:", np.asarray(out)[0, 16:].tolist())


if __name__ == "__main__":
    main()
