#!/usr/bin/env python
"""KV-cached generation example: greedy, sampling and beam search side by side.

Uses a freshly initialized tiny GPT-2 (random weights — the point is the decode
machinery; load a checkpoint via engine.load_checkpoint for real text).

    python examples/generate_text.py --beams 4 --top-p 0.9
"""

import argparse
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--new-tokens", type=int, default=24)
    p.add_argument("--beams", type=int, default=4)
    p.add_argument("--temperature", type=float, default=0.9)
    p.add_argument("--top-k", type=int, default=40)
    p.add_argument("--top-p", type=float, default=0.95)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=128, n_layer=4,
                     n_head=4, compute_dtype=jnp.float32)
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.random.default_rng(1).integers(0, 512, (1, 8)),
                         jnp.int32)

    greedy = model.generate(params, prompt, args.new_tokens)
    sampled = model.generate(params, prompt, args.new_tokens,
                             temperature=args.temperature, top_k=args.top_k,
                             top_p=args.top_p, rng=jax.random.PRNGKey(2))
    beams, scores = model.beam_search(params, prompt, args.new_tokens,
                                      num_beams=args.beams, length_penalty=0.9)

    print("prompt :", np.asarray(prompt)[0].tolist())
    print("greedy :", np.asarray(greedy)[0, 8:].tolist())
    print("sampled:", np.asarray(sampled)[0, 8:].tolist())
    print(f"beam-{args.beams} (score {float(scores[0]):.3f}):",
          np.asarray(beams)[0, 8:].tolist())


if __name__ == "__main__":
    main()
