#!/usr/bin/env python
"""Minimal BERT masked-LM pretraining example (the bert-pretraining analog).

Synthetic structured tokens + 15% masking; fused transformer layers inside.

    python examples/train_bert_mlm.py --steps 20
    python examples/train_bert_mlm.py --lamb          # large-batch LAMB recipe
"""

import argparse
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=2048)
    p.add_argument("--lamb", action="store_true")
    args = p.parse_args()

    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM

    cfg = BertConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                     num_hidden_layers=args.layers, num_attention_heads=args.heads,
                     intermediate_size=4 * args.hidden,
                     max_position_embeddings=args.seq,
                     use_flash_attention=jax.default_backend() == "tpu")
    model = BertForMaskedLM(cfg)
    params = model.init(jax.random.PRNGKey(0))

    opt = ({"type": "Lamb", "params": {"lr": 2e-3}} if args.lamb
           else {"type": "Adam", "params": {"lr": 5e-4}})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config_params={"train_batch_size": args.batch, "steps_per_print": 5,
                       "bf16": {"enabled": True},
                       "optimizer": opt,
                       "zero_optimization": {"stage": 2}})

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        base = rng.integers(0, args.vocab, size=(args.batch, args.seq // 4))
        ids = np.repeat(base, 4, axis=1).astype(np.int32)  # learnable repetition
        mask = rng.random(ids.shape) < 0.15
        labels = np.where(mask, ids, -100).astype(np.int32)
        inputs = ids.copy()
        inputs[mask] = 0  # [MASK]
        loss = engine(inputs, labels)
        engine.backward(loss)
        engine.step()
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  mlm loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
